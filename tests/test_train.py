"""Training machinery tests: losses, state, and the numerics tier of
SURVEY.md §4 — loss decreases over N steps per model family (the reference's
implicit correctness criterion)."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from machine_learning_apache_spark_tpu.models import MLP, LSTMClassifier, TinyVGG
from machine_learning_apache_spark_tpu.train import (
    TrainState,
    classification_loss,
    cross_entropy,
    evaluate,
    fit,
    make_optimizer,
    masked_token_cross_entropy,
)


class TestLosses:
    def test_cross_entropy_matches_manual(self, rng):
        logits = jnp.asarray(rng.standard_normal((8, 5)), dtype=jnp.float32)
        labels = jnp.asarray(rng.integers(0, 5, 8))
        expected = -np.mean(
            np.log(np.asarray(jax.nn.softmax(logits)))[np.arange(8), np.asarray(labels)]
        )
        np.testing.assert_allclose(float(cross_entropy(logits, labels)), expected, rtol=1e-5)

    def test_masked_ce_ignores_pad(self, rng):
        logits = jnp.asarray(rng.standard_normal((2, 6, 5)), dtype=jnp.float32)
        labels = jnp.asarray([[1, 2, 3, 0, 0, 0], [4, 1, 0, 0, 0, 0]])
        loss = masked_token_cross_entropy(logits, labels, pad_id=0)
        # Equals the mean CE over just the 5 non-pad tokens
        # (pytorch_machine_translator.py:182-188 semantics).
        per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        mask = np.asarray(labels) != 0
        expected = float(np.asarray(per_tok)[mask].mean())
        np.testing.assert_allclose(float(loss), expected, rtol=1e-5)

    def test_masked_ce_pad_logits_irrelevant(self, rng):
        logits = jnp.asarray(rng.standard_normal((1, 4, 5)), dtype=jnp.float32)
        labels = jnp.asarray([[2, 1, 0, 0]])
        loss1 = masked_token_cross_entropy(logits, labels)
        logits2 = logits.at[0, 2:].add(37.0)
        loss2 = masked_token_cross_entropy(logits2, labels)
        np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-6)

    def test_classification_loss_last_valid_position(self, rng):
        """``pad_id`` switches the last-timestep head from the final column
        to each row's last non-pad position — the correct-semantics variant
        of ``pred[:, -1, :]`` (``pytorch_lstm.py:160``) for end-padded rows."""
        seqs = jnp.asarray([[5, 3, 7, 0, 0], [2, 0, 0, 0, 0], [4, 4, 4, 4, 4]])
        labels = jnp.asarray([1, 0, 2])
        logits = jnp.asarray(
            rng.standard_normal((3, 5, 3)), dtype=jnp.float32
        )

        apply_fn = lambda vars_, x, **kw: logits  # model stub
        loss_fn = classification_loss(apply_fn, last_timestep=True, pad_id=0)
        loss, aux = loss_fn({}, (seqs, labels), jax.random.key(0))
        # rows' last valid positions: 2, 0, 4
        picked = logits[jnp.arange(3), jnp.asarray([2, 0, 4])]
        np.testing.assert_allclose(
            float(loss), float(cross_entropy(picked, labels)), rtol=1e-6
        )
        # default semantics still reads the final column
        loss_fn_ref = classification_loss(apply_fn, last_timestep=True)
        loss_ref, _ = loss_fn_ref({}, (seqs, labels), jax.random.key(0))
        np.testing.assert_allclose(
            float(loss_ref), float(cross_entropy(logits[:, -1, :], labels)),
            rtol=1e-6,
        )


def _synthetic_classification(rng, n=120, features=4, classes=3):
    """4-feature/3-class data shaped like the MLlib libsvm sample
    (mllib_multilayer_perceptron_classifier.py:32) — linearly separable-ish."""
    centers = rng.standard_normal((classes, features)) * 3
    labels = rng.integers(0, classes, n)
    feats = centers[labels] + rng.standard_normal((n, features)) * 0.5
    return feats.astype(np.float32), labels.astype(np.int64)


def _batches(features, labels, batch_size):
    out = []
    for i in range(0, len(labels), batch_size):
        out.append((jnp.asarray(features[i : i + batch_size]),
                    jnp.asarray(labels[i : i + batch_size])))
    return out


class TestFitMLP:
    """The minimum end-to-end slice (SURVEY.md §7 step 2): MLP 4-5-4-3,
    sigmoid, SGD(0.03), CE — mirrors pytorch_multilayer_perceptron.py."""

    def test_loss_decreases_and_learns(self, rng):
        feats, labels = _synthetic_classification(rng)
        model = MLP(layers=(4, 5, 4, 3))
        params = model.init(jax.random.key(0), feats[:1])["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer("sgd", 0.03)
        )
        loss_fn = classification_loss(model.apply)
        batches = _batches(feats, labels, 30)
        result = fit(state, loss_fn, batches, epochs=100, log_every=0)
        assert result.history[-1]["loss"] < result.history[0]["loss"]
        metrics = evaluate(result.state, loss_fn, batches, emit=lambda s: None)
        # Deterministic-seed bound, not an aspiration: this exact
        # data/init/optimizer draw reaches 69.2% on the pinned CPU stack
        # (3-class baseline 33%). The old 80% bound was tuned on a
        # different seed and failed spuriously here.
        assert metrics["accuracy"] > 60.0
        assert result.train_seconds > 0

    def test_evaluate_consumes_every_sample(self, rng):
        """Full-test-set eval (``pytorch_cnn.py:154-176`` consumes the whole
        loader): a ragged tail batch that doesn't divide the mesh's data
        axis must still be scored — unsharded — not silently dropped."""
        from machine_learning_apache_spark_tpu.parallel import make_mesh
        from machine_learning_apache_spark_tpu.parallel.mesh import DATA_AXIS

        feats, labels = _synthetic_classification(rng, n=37)
        model = MLP(layers=(4, 5, 4, 3))
        params = model.init(jax.random.key(0), feats[:1])["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer("sgd", 0.03)
        )
        mesh = make_mesh({DATA_AXIS: 8})
        batches = _batches(feats, labels, 16)  # 16, 16, 5 — ragged tail
        metrics = evaluate(
            state, classification_loss(model.apply, train=False), batches,
            mesh=mesh, emit=lambda s: None,
        )
        assert metrics["eval_samples"] == 37

    def test_sync_check_flag(self, rng):
        """sync_check_every wires the replica-divergence race detector into
        the loop (trivially 0.0 single-process; the 2-process gang test
        exercises the cross-process path)."""
        feats, labels = _synthetic_classification(rng, n=60)
        model = MLP(layers=(4, 5, 4, 3))
        params = model.init(jax.random.key(0), feats[:1])["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer("sgd", 0.03)
        )
        lines = []
        fit(
            state, classification_loss(model.apply),
            _batches(feats, labels, 30),
            epochs=4, log_every=0, sync_check_every=2, emit=lines.append,
        )
        assert sum("replica divergence" in l for l in lines) == 2

    def test_step_counter_advances(self, rng):
        feats, labels = _synthetic_classification(rng, n=30)
        model = MLP(layers=(4, 5, 4, 3))
        params = model.init(jax.random.key(0), feats[:1])["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer("sgd", 0.03)
        )
        result = fit(state, classification_loss(model.apply), _batches(feats, labels, 30),
                     epochs=3, log_every=0)
        assert int(result.state.step) == 3


class TestStepsPerCall:
    """``fit(steps_per_call=K)`` — the scanned multi-step trainer
    (``make_multi_step``: K steps fused into one dispatch via ``lax.scan``,
    the dispatch-overhead amortization for small/fast models) — must be a
    pure performance knob: same rng stream, same step order, numerically
    identical params and identical epoch metrics."""

    def _run(self, k, *, n=60, bs=10, epochs=2, mesh=None, seed=3):
        data_rng = np.random.default_rng(seed)
        feats, labels = _synthetic_classification(data_rng, n=n)
        model = MLP(layers=(4, 5, 4, 3))
        params = model.init(jax.random.key(0), feats[:1])["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer("sgd", 0.03)
        )
        return fit(
            state, classification_loss(model.apply),
            _batches(feats, labels, bs), epochs=epochs, log_every=0,
            rng=jax.random.key(7), steps_per_call=k, mesh=mesh,
        )

    def _assert_same(self, r1, rk):
        for a, b in zip(
            jax.tree.leaves(r1.state.params), jax.tree.leaves(rk.state.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            )
        for h1, hk in zip(r1.history, rk.history):
            np.testing.assert_allclose(h1["loss"], hk["loss"], rtol=1e-5)
            np.testing.assert_allclose(
                h1["accuracy"], hk["accuracy"], rtol=1e-5
            )

    def test_parity_exact_groups(self):
        # 6 batches/epoch, K=3 → two full groups per epoch, no remainder.
        self._assert_same(self._run(1), self._run(3))

    def test_parity_ragged_tail(self):
        # 6 batches/epoch, K=4 → one scanned group + 2 single-step batches:
        # the ragged fallback must keep the same rng/step sequence.
        self._assert_same(self._run(1), self._run(4))

    def test_parity_group_larger_than_epoch(self):
        # K=16 > 6 batches/epoch → every batch takes the single-step path.
        self._assert_same(self._run(1), self._run(16))

    def test_parity_on_mesh(self):
        from machine_learning_apache_spark_tpu.parallel import make_mesh
        from machine_learning_apache_spark_tpu.parallel.mesh import DATA_AXIS

        mesh = make_mesh({DATA_AXIS: 8})
        # bs=16 → 8-divisible batches; shard_batch_stack places [K, B, ...]
        # with dim 1 on the data axis.
        self._assert_same(
            self._run(1, n=64, bs=16, mesh=mesh),
            self._run(2, n=64, bs=16, mesh=mesh),
        )

    def test_step_counter_counts_real_steps(self):
        r = self._run(3, n=60, bs=10, epochs=2)  # 12 batches total
        assert int(r.state.step) == 12

    def test_invalid_steps_per_call(self):
        with pytest.raises(ValueError, match="steps_per_call"):
            self._run(0)


class TestDevicePrefetch:
    """``fit(prefetch_to_device=N)`` — sharded transfers issued N batches
    ahead (``parallel.device_prefetch``) — must be a pure pipelining knob:
    identical values, identical rng stream, epoch boundaries intact."""

    def _run(self, prefetch, epochs=2):
        from machine_learning_apache_spark_tpu.parallel import make_mesh
        from machine_learning_apache_spark_tpu.parallel.mesh import DATA_AXIS

        data_rng = np.random.default_rng(5)
        feats, labels = _synthetic_classification(data_rng, n=64)
        model = MLP(layers=(4, 5, 4, 3))
        params = model.init(jax.random.key(0), feats[:1])["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer("sgd", 0.03)
        )
        return fit(
            state, classification_loss(model.apply),
            _batches(feats, labels, 16), epochs=epochs, log_every=0,
            rng=jax.random.key(7), mesh=make_mesh({DATA_AXIS: 8}),
            prefetch_to_device=prefetch,
        )

    def test_parity(self):
        r0, r2 = self._run(0), self._run(2)
        for a, b in zip(
            jax.tree.leaves(r0.state.params), jax.tree.leaves(r2.state.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            )
        for h0, h2 in zip(r0.history, r2.history):
            np.testing.assert_allclose(h0["loss"], h2["loss"], rtol=1e-5)

    def test_depth_larger_than_epoch(self):
        # depth 16 > 4 batches/epoch: the tail drain must still yield all.
        r = self._run(16)
        assert int(r.state.step) == 8  # 4 batches × 2 epochs

    def test_invalid_depth(self):
        from machine_learning_apache_spark_tpu.parallel import (
            device_prefetch,
            make_mesh,
        )
        from machine_learning_apache_spark_tpu.parallel.mesh import DATA_AXIS

        with pytest.raises(ValueError, match="depth"):
            list(device_prefetch([], make_mesh({DATA_AXIS: 8}), depth=0))


class TestOptimizerKnobs:
    """Schedules, clipping, accumulation — training-scale knobs the
    reference's fixed-lr SGD/Adam lacks (SURVEY.md §2.3 headroom)."""

    def test_warmup_cosine_shape(self):
        from machine_learning_apache_spark_tpu.train.state import make_schedule

        sched = make_schedule(
            1e-3, "warmup_cosine", warmup_steps=10, total_steps=100
        )
        assert float(sched(0)) == 0.0
        np.testing.assert_allclose(float(sched(10)), 1e-3, rtol=1e-6)
        assert float(sched(50)) < 1e-3
        assert float(sched(100)) < float(sched(50))

    def test_cosine_requires_total_steps(self):
        with pytest.raises(ValueError, match="total_steps"):
            make_optimizer("adam", 1e-3, schedule="cosine")

    def test_cosine_honors_warmup(self):
        from machine_learning_apache_spark_tpu.train.state import make_schedule

        sched = make_schedule(
            1e-3, "cosine", warmup_steps=10, total_steps=100
        )
        assert float(sched(0)) == 0.0  # warmup not silently dropped
        np.testing.assert_allclose(float(sched(10)), 1e-3, rtol=1e-6)

    def test_grad_clip_caps_update(self):
        params = {"w": jnp.zeros(4)}
        huge = {"w": jnp.full(4, 1e6)}
        tx = make_optimizer("sgd", 1.0, grad_clip=1.0)
        updates, _ = tx.update(huge, tx.init(params), params)
        norm = float(jnp.linalg.norm(updates["w"]))
        np.testing.assert_allclose(norm, 1.0, rtol=1e-5)

    def test_accumulation_matches_big_batch(self, rng):
        """K microbatch updates under MultiSteps(K) == one SGD update on the
        concatenated batch (grad-mean linearity)."""
        feats, labels = _synthetic_classification(rng, n=60)
        model = MLP(layers=(4, 5, 4, 3))
        params = model.init(jax.random.key(0), feats[:1])["params"]
        loss_fn = classification_loss(model.apply, train=False)

        accum = TrainState.create(
            apply_fn=model.apply, params=params,
            tx=make_optimizer("sgd", 0.1, accumulate_steps=2),
        )
        big = TrainState.create(
            apply_fn=model.apply, params=params,
            tx=make_optimizer("sgd", 0.1),
        )
        rng_key = jax.random.key(1)
        for batch in _batches(feats, labels, 30):  # two microbatches of 30
            grads = jax.grad(lambda p: loss_fn(p, batch, rng_key)[0])(
                accum.params
            )
            accum = accum.apply_gradients(grads)
        full = (jnp.asarray(feats), jnp.asarray(labels))
        big = big.apply_gradients(
            jax.grad(lambda p: loss_fn(p, full, rng_key)[0])(big.params)
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6
            ),
            accum.params,
            big.params,
        )

    def test_fit_with_accumulation_learns(self, rng):
        feats, labels = _synthetic_classification(rng)
        model = MLP(layers=(4, 5, 4, 3))
        params = model.init(jax.random.key(0), feats[:1])["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params,
            tx=make_optimizer("sgd", 0.03, accumulate_steps=2),
        )
        batches = _batches(feats, labels, 30)
        result = fit(
            state, classification_loss(model.apply), batches,
            epochs=100, log_every=0,
        )
        assert result.history[-1]["loss"] < result.history[0]["loss"]


class TestMetricsLogger:
    """Structured JSONL metrics sink — the observability counterpart of the
    reference's print-only metrics (SURVEY.md §5)."""

    def test_fit_writes_epoch_and_run_records(self, rng, tmp_path):
        from machine_learning_apache_spark_tpu.train.metrics import (
            MetricsLogger,
        )

        feats, labels = _synthetic_classification(rng, n=60)
        model = MLP(layers=(4, 5, 4, 3))
        params = model.init(jax.random.key(0), feats[:1])["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer("sgd", 0.03)
        )
        path = str(tmp_path / "metrics.jsonl")
        fit(
            state, classification_loss(model.apply),
            _batches(feats, labels, 30),
            epochs=3, log_every=0, metrics_file=path,
        )
        records = MetricsLogger.read(path)
        epochs = [r for r in records if r["kind"] == "epoch"]
        runs = [r for r in records if r["kind"] == "run"]
        assert len(epochs) == 3 and len(runs) == 1
        assert all("loss" in r and "ts" in r and "step" in r for r in epochs)
        assert runs[0]["epochs"] == 3 and runs[0]["train_seconds"] > 0

    def test_recipe_flag_appends_across_runs(self, rng, tmp_path):
        from machine_learning_apache_spark_tpu.recipes.mlp import train_mlp
        from machine_learning_apache_spark_tpu.train.metrics import (
            MetricsLogger,
        )

        path = str(tmp_path / "m.jsonl")
        train_mlp(epochs=2, synthetic_n=120, metrics_path=path)
        train_mlp(epochs=2, synthetic_n=120, metrics_path=path)
        records = MetricsLogger.read(path)
        assert len([r for r in records if r["kind"] == "run"]) == 2
        # eval results land in the same sink (one per run)
        evals = [r for r in records if r["kind"] == "eval"]
        assert len(evals) == 2 and all("accuracy" in r for r in evals)


class TestFitCNN:
    def test_loss_decreases(self, rng):
        # Tiny synthetic FashionMNIST-shaped batch; 20 steps of SGD(0.01).
        images = rng.standard_normal((32, 28, 28, 1)).astype(np.float32)
        labels = rng.integers(0, 10, 32).astype(np.int64)
        model = TinyVGG(hidden_units=4, num_classes=10)
        params = model.init(jax.random.key(0), jnp.asarray(images[:1]))["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer("sgd", 0.05)
        )
        batches = [(jnp.asarray(images), jnp.asarray(labels))]
        result = fit(state, classification_loss(model.apply), batches,
                     epochs=20, log_every=0)
        assert result.history[-1]["loss"] < result.history[0]["loss"] * 0.9


class TestFitLSTM:
    def test_loss_decreases(self, rng):
        # Token sequences whose class is determined by the dominant token id
        # band — learnable by the embedding alone.
        n, seq, vocab, classes = 64, 12, 40, 4
        labels = rng.integers(0, classes, n)
        toks = np.stack([
            rng.integers(lbl * 10, lbl * 10 + 10, seq) for lbl in labels
        ]).astype(np.int32)
        model = LSTMClassifier(vocab_size=vocab, embed_dim=8, hidden_size=16,
                               num_classes=classes)
        params = model.init(jax.random.key(0), jnp.asarray(toks[:1]))["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer("adam", 1e-2)
        )
        loss_fn = classification_loss(model.apply, last_timestep=True)
        batches = [(jnp.asarray(toks), jnp.asarray(labels.astype(np.int64)))]
        result = fit(state, loss_fn, batches, epochs=30, log_every=0)
        assert result.history[-1]["loss"] < result.history[0]["loss"] * 0.5
