"""Ulysses sequence parallelism: exact parity with dense attention on the
8-virtual-device CPU mesh, dispatch routing, and validation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from machine_learning_apache_spark_tpu.ops.attention import (
    scaled_dot_product_attention,
    sequence_parallel,
)
from machine_learning_apache_spark_tpu.ops.masks import (
    combine_masks,
    make_causal_mask,
)
from machine_learning_apache_spark_tpu.parallel import make_mesh
from machine_learning_apache_spark_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS
from machine_learning_apache_spark_tpu.parallel.ulysses_attention import (
    ulysses_attention,
)


def _qkv(b=2, h=8, s=16, d=4, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (b, h, s, d)) for k in ks)


def _dense(q, k, v, causal=False, kv_valid=None):
    mask = None
    if kv_valid is not None:
        mask = kv_valid[:, None, None, :]
    if causal:
        mask = combine_masks(mask, make_causal_mask(q.shape[2]))
    return scaled_dot_product_attention(q, k, v, mask)


class TestUlyssesParity:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        mesh = make_mesh({DATA_AXIS: 2, SEQ_AXIS: 4})
        q, k, v = _qkv()
        out = ulysses_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_dense(q, k, v, causal)), atol=1e-5
        )

    def test_kv_valid_rides(self):
        mesh = make_mesh({SEQ_AXIS: 8})
        q, k, v = _qkv(b=3, h=8, s=24)
        valid = jax.random.uniform(jax.random.key(7), (3, 24)) > 0.3
        valid = valid.at[:, 0].set(True)  # no fully-padded rows here
        out = ulysses_attention(q, k, v, mesh, causal=True, kv_valid=valid)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(_dense(q, k, v, causal=True, kv_valid=valid)),
            atol=1e-5,
        )

    def test_fully_padded_rows_emit_zeros(self):
        """The ring/flash convention on every backend: an all-pad row
        outputs exact zeros, never the mean of V."""
        from machine_learning_apache_spark_tpu.parallel.ring_attention import (
            ring_attention,
        )

        mesh = make_mesh({SEQ_AXIS: 8})
        q, k, v = _qkv(b=2, h=8, s=16)
        valid = jnp.ones((2, 16), bool).at[1, :].set(False)  # row 1 all pad
        out_u = ulysses_attention(q, k, v, mesh, causal=True, kv_valid=valid)
        out_r = ring_attention(q, k, v, mesh, causal=True, kv_valid=valid)
        assert bool((out_u[1] == 0.0).all())
        np.testing.assert_allclose(
            np.asarray(out_u), np.asarray(out_r), atol=1e-5
        )

    def test_gradients_match_dense(self):
        mesh = make_mesh({DATA_AXIS: 2, SEQ_AXIS: 4})
        q, k, v = _qkv(h=4)
        g_u = jax.grad(
            lambda q: (ulysses_attention(q, k, v, mesh, causal=True) ** 2).sum()
        )(q)
        g_d = jax.grad(
            lambda q: (_dense(q, k, v, causal=True) ** 2).sum()
        )(q)
        np.testing.assert_allclose(np.asarray(g_u), np.asarray(g_d), atol=1e-4)

    def test_jit(self):
        mesh = make_mesh({SEQ_AXIS: 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(h=4, s=12)
        out = jax.jit(
            lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=True)
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_dense(q, k, v, causal=True)),
            atol=1e-5,
        )


class TestUlyssesValidation:
    def test_head_divisibility(self):
        mesh = make_mesh({SEQ_AXIS: 8})
        q, k, v = _qkv(h=6)  # 6 % 8 != 0
        with pytest.raises(ValueError, match="num_heads"):
            ulysses_attention(q, k, v, mesh)

    def test_seq_divisibility(self):
        mesh = make_mesh({SEQ_AXIS: 8})
        q, k, v = _qkv(s=12)  # 12 % 8 != 0
        with pytest.raises(ValueError, match="not divisible"):
            ulysses_attention(q, k, v, mesh)

    def test_method_validated(self):
        mesh = make_mesh({SEQ_AXIS: 8})
        with pytest.raises(ValueError, match="method"):
            with sequence_parallel(mesh, method="spiral"):
                pass


class TestUlyssesDispatch:
    def test_context_routes_to_ulysses(self, monkeypatch):
        """sequence_parallel(method='ulysses') engages the all_to_all path
        (counted — a silent fall-through to ring/dense must fail)."""
        import importlib

        ua = importlib.import_module(
            "machine_learning_apache_spark_tpu.parallel.ulysses_attention"
        )
        calls = {"n": 0}
        orig = ua.ulysses_attention

        def counting(*args, **kwargs):
            calls["n"] += 1
            return orig(*args, **kwargs)

        monkeypatch.setattr(ua, "ulysses_attention", counting)
        from machine_learning_apache_spark_tpu.ops.attention import (
            dot_product_attention,
        )

        mesh = make_mesh({DATA_AXIS: 2, SEQ_AXIS: 4})
        q, k, v = _qkv()
        with sequence_parallel(mesh, method="ulysses"):
            out = dot_product_attention(q, k, v, causal=True)
        assert calls["n"] == 1
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_dense(q, k, v, causal=True)),
            atol=1e-5,
        )

    def test_indivisible_heads_raise_in_dispatch(self):
        from machine_learning_apache_spark_tpu.ops.attention import (
            dot_product_attention,
        )

        mesh = make_mesh({DATA_AXIS: 2, SEQ_AXIS: 4})
        q, k, v = _qkv(h=6)  # 6 % 4 != 0
        with sequence_parallel(mesh, method="ulysses"):
            with pytest.raises(ValueError, match="ulysses"):
                dot_product_attention(q, k, v, causal=True)

    def test_recipe_flag(self):
        """sequence_parallel_method reachable from the recipe surface."""
        from machine_learning_apache_spark_tpu.recipes.translation import (
            train_translator,
        )

        out = train_translator(
            epochs=1, synthetic_n=128, batch_size=8, max_len=16,
            d_model=32, ffn_hidden=64, num_heads=4, log_every=0,
            sequence_parallel=4, sequence_parallel_method="ulysses",
        )
        assert out["history"][-1]["loss"] < 7.0
        with pytest.raises(ValueError, match="ulysses"):
            train_translator(
                epochs=1, synthetic_n=64, batch_size=8, max_len=16,
                d_model=30, ffn_hidden=64, num_heads=6, log_every=0,
                sequence_parallel=4, sequence_parallel_method="ulysses",
            )
