"""Test bootstrap: force an 8-virtual-device CPU mesh BEFORE jax import.

This is the JAX analogue of the reference's fake cluster — TorchDistributor
``local_mode=True`` (``distributed_multilayer_perceptron.py:179``) and the
manual ``MASTER_ADDR=localhost`` rendezvous block
(``pytorch_multilayer_perceptron.py:15-21``) — letting every distributed code
path run on one CPU host (SURVEY.md §4).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

# Request 8 virtual CPU devices BEFORE jax can initialize a backend. On
# jax >= 0.4.34 the config option below is authoritative; on older builds
# (and on builds where the option is absent, like the installed 0.4.37)
# the XLA flag is the only lever, and it must be in the environment before
# the CPU client is created. Appending (not overwriting) preserves any
# flags the hosting image set.
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
) and "jax" not in sys.modules:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

# The hosting image may pre-import jax from sitecustomize (axon PJRT plugin),
# in which case env vars are too late — use the config API, which works any
# time before first backend initialization.
import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax 0.4.37 predates jax_num_cpu_devices; XLA_FLAGS above covers it
    # (unless jax was pre-imported, in which case the device count is
    # whatever the importer chose and mesh-shape-sensitive tests skip).
    pass

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def pytest_sessionfinish(session, exitstatus):
    """Sweep stray gang process groups at session end: a launcher test
    that timed out or crashed mid-gang must not leave orphaned ranks
    burning CPU past the pytest run (they would also hold the session's
    coordinator ports open). No-op (returns 0) in any healthy run."""
    del session, exitstatus
    try:
        from machine_learning_apache_spark_tpu.launcher.distributor import (
            kill_stray_gangs,
        )
    except Exception:
        return  # collection-only / broken-import runs have nothing to sweep
    kill_stray_gangs()
