"""Test bootstrap: force an 8-virtual-device CPU mesh BEFORE jax import.

This is the JAX analogue of the reference's fake cluster — TorchDistributor
``local_mode=True`` (``distributed_multilayer_perceptron.py:179``) and the
manual ``MASTER_ADDR=localhost`` rendezvous block
(``pytorch_multilayer_perceptron.py:15-21``) — letting every distributed code
path run on one CPU host (SURVEY.md §4).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"

# The hosting image may pre-import jax from sitecustomize (axon PJRT plugin),
# in which case env vars are too late — use the config API, which works any
# time before first backend initialization.
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
