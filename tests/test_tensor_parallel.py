"""Tensor parallelism: logical-axis mapping, TP forward parity with the
single-device model, and the driver's dp×tp dry run.

The reference has no TP (SURVEY.md §2.3); the contract here is purely
internal consistency — sharding must never change the math.
"""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from machine_learning_apache_spark_tpu.models import Transformer, TransformerConfig
from machine_learning_apache_spark_tpu.parallel import (
    DATA_AXIS,
    MODEL_AXIS,
    logical_to_mesh_spec,
    make_mesh,
    mesh_shardings,
    shard_params,
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(
        src_vocab_size=64,
        trg_vocab_size=80,
        d_model=16,
        ffn_hidden=32,
        num_heads=4,
        num_layers=2,
        max_len=16,
        dropout=0.0,
    )
    model = Transformer(cfg)
    rng = jax.random.key(0)
    src = jax.random.randint(rng, (4, 12), 1, 64, dtype=jnp.int32)
    trg = jax.random.randint(rng, (4, 10), 1, 80, dtype=jnp.int32)
    variables = model.init(rng, src, trg)
    return model, variables, src, trg


class TestLogicalToMeshSpec:
    def test_known_names_map_to_model_axis(self, mesh):
        assert logical_to_mesh_spec(P("embed", "heads"), mesh) == P(None, MODEL_AXIS)
        assert logical_to_mesh_spec(P("mlp", "embed"), mesh) == P(MODEL_AXIS, None)

    def test_unknown_name_replicates(self, mesh):
        assert logical_to_mesh_spec(P("mystery"), mesh) == P(None)

    def test_missing_mesh_axis_collapses(self):
        dp_only = make_mesh({DATA_AXIS: 8})
        assert logical_to_mesh_spec(P("embed", "heads"), dp_only) == P(None, None)

    def test_tuple_entries(self, mesh):
        assert logical_to_mesh_spec(P(("batch", "seq"), "heads"), mesh) == P(
            (DATA_AXIS,), MODEL_AXIS
        )


class TestShardParams:
    def test_kernels_sharded_biases_replicated(self, tiny, mesh):
        model, variables, *_ = tiny
        params = shard_params(variables["params"], mesh)
        ffn_up = params["encoder"]["layer_0"]["ffn"]["up"]
        assert ffn_up["kernel"].sharding.spec == P(None, MODEL_AXIS)
        assert ffn_up["bias"].sharding.spec == P()

    def test_shardings_tree_matches_params(self, tiny, mesh):
        _, variables, *_ = tiny
        sh = mesh_shardings(variables["params"], mesh)
        import flax.linen as nn

        assert jax.tree.structure(sh) == jax.tree.structure(
            nn.unbox(variables["params"])
        )

    def test_tp_forward_matches_unsharded(self, tiny, mesh):
        model, variables, src, trg = tiny
        import flax.linen as nn

        expected = model.apply(nn.unbox(variables), src, trg)
        params = shard_params(variables["params"], mesh)
        got = jax.jit(lambda p, s, t: model.apply({"params": p}, s, t))(
            params, src, trg
        )
        assert jnp.allclose(expected, got, atol=1e-5)


class TestGraftEntry:
    def test_dryrun_multichip(self):
        import __graft_entry__ as g

        g.dryrun_multichip(8)

    def test_entry_traces(self):
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.eval_shape(fn, *args)
        assert out.shape == (8, 128, 10240)


class TestZero1:
    """ZeRO stage 1 (shard_state(zero1=True)): optimizer moments shard 1/N
    over the data axis; training is numerically equivalent (float reduction
    order may differ at the last-ulp level)."""

    def _fit_mlp(self, zero1):
        import numpy as np

        from machine_learning_apache_spark_tpu.data import (
            ArrayDataset,
            DataLoader,
        )
        from machine_learning_apache_spark_tpu.models import MLP
        from machine_learning_apache_spark_tpu.parallel import (
            data_parallel_mesh,
            params_fingerprint,
        )
        from machine_learning_apache_spark_tpu.train.loop import (
            classification_loss,
            fit,
        )
        from machine_learning_apache_spark_tpu.train.state import (
            TrainState,
            make_optimizer,
        )

        rng = np.random.default_rng(0)
        # 16-dim features: kernel leading dims (16, 32) divide the 8-way
        # data axis so moments genuinely shard; biases ([32], [3]) cover
        # both the sharded and the non-divisible-fallback cases.
        feats = rng.normal(size=(64, 16)).astype(np.float32)
        labels = rng.integers(0, 3, 64).astype(np.int64)
        model = MLP(layers=(16, 32, 3))
        params = model.init(jax.random.key(0), jnp.ones((1, 16)))["params"]
        state = TrainState.create(
            apply_fn=model.apply,
            params=params,
            tx=make_optimizer("adam", 1e-2),
        )
        loader = DataLoader(
            ArrayDataset(feats, labels), 16, shuffle=False, drop_last=True
        )
        result = fit(
            state,
            classification_loss(model.apply),
            loader,
            epochs=3,
            rng=jax.random.key(1),
            mesh=data_parallel_mesh(),
            log_every=0,
            zero1=zero1,
        )
        return result, params_fingerprint(result.state.params)

    def test_trajectory_identical_and_moments_sharded(self):
        import numpy as np

        base, fp_base = self._fit_mlp(zero1=False)
        z1, fp_z1 = self._fit_mlp(zero1=True)
        # Numerically equivalent training (sharded moments change float
        # reduction order at the ~1e-7 level, never the math): same
        # per-epoch loss trajectory and final params within float32 noise.
        np.testing.assert_allclose(
            [h["loss"] for h in z1.history],
            [h["loss"] for h in base.history],
            rtol=1e-5,
        )
        assert fp_z1 == pytest.approx(fp_base, rel=1e-4)
        # At least one Adam moment actually landed sharded over "data".
        specs = [
            tuple(leaf.sharding.spec)
            for leaf in jax.tree.leaves(z1.state.opt_state)
            if getattr(leaf, "ndim", 0) >= 1
        ]
        assert any(DATA_AXIS in jax.tree.leaves(s) for s in specs), specs

    def test_divisibility_fallback_replicates(self):
        """Leaves the data axis cannot divide stay replicated (loudly via
        _divisible_sharding) instead of crashing placement."""
        z1, _ = self._fit_mlp(zero1=True)
        for leaf in jax.tree.leaves(z1.state.opt_state):
            if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] % 8:
                assert DATA_AXIS not in jax.tree.leaves(
                    tuple(leaf.sharding.spec)
                )
