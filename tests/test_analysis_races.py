"""Races the ``locks`` lint pass flagged, pinned under real thread load.

The static pass (docs/STATIC_ANALYSIS.md) found two quarantine-adjacent
races when the ``# guarded-by:`` declarations went in:

- ``serving.engine``: the /healthz window timestamps were written by the
  decode worker and read pairwise by scrape threads with two bare loads —
  a reader could pair a fresh ok-batch time with a stale quarantine time
  and report "recovered" mid-degraded-window. Fixed by ``_HealthWindow``
  (both fields guarded by one lock, snapshot under it).
- ``telemetry.http``: ``start_http_server`` published ``_SERVER`` and
  released the lock *before* assigning ``sidecar_path``, so a concurrent
  ``stop_http_server`` could retire the server while its sidecar write
  was still in flight — leaking an ``http_rank<k>.json`` past the
  server's death. Fixed by writing the sidecar before publication,
  inside the lock.

Each test here drives the fixed code from 4 threads and asserts the
invariant the race used to break.
"""

import glob
import os
import threading
import time

import pytest

from machine_learning_apache_spark_tpu import telemetry
from machine_learning_apache_spark_tpu.telemetry import events, http

STRESS_SECONDS = 0.4


# -- serving: the /healthz quarantine window -----------------------------------
@pytest.mark.serving
class TestHealthWindow:
    def test_recovered_semantics(self):
        from machine_learning_apache_spark_tpu.serving.engine import (
            _HealthWindow,
        )

        w = _HealthWindow()
        assert w.recovered()  # never quarantined
        w.note_quarantine(1.0)
        assert not w.recovered()  # degraded until a batch lands
        w.note_ok_batch(2.0)
        assert w.recovered()
        w.note_quarantine(3.0)
        assert not w.recovered()  # re-quarantined after the ok batch
        assert w.snapshot() == (3.0, 2.0)

    def test_snapshot_pair_is_consistent_under_4_threads(self):
        """1 writer + 3 readers. The writer advances in lockstep pairs
        (quarantine at i, then ok-batch at i), so at every instant the
        true state satisfies ``lq - 1 <= lok <= lq``. A torn pair read
        observes ``lok > lq`` (stale quarantine + fresh ok) and falsely
        reports recovery — possible whenever the two loads can be split
        by a thread switch, which the lock rules out structurally rather
        than leaving to CPython's bytecode-level switch points."""
        from machine_learning_apache_spark_tpu.serving.engine import (
            _HealthWindow,
        )

        w = _HealthWindow()
        stop = threading.Event()
        violations: list[tuple] = []

        def writer():
            i = 0.0
            while not stop.is_set():
                i += 1.0
                w.note_quarantine(i)
                w.note_ok_batch(i)

        def reader():
            while not stop.is_set():
                lq, lok = w.snapshot()
                if lq is None:
                    if lok is not None:
                        violations.append((lq, lok))
                elif lok is not None and not (lq - 1.0 <= lok <= lq):
                    violations.append((lq, lok))

        threads = [threading.Thread(target=writer)] + [
            threading.Thread(target=reader) for _ in range(3)
        ]
        for t in threads:
            t.start()
        time.sleep(STRESS_SECONDS)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not violations, violations[:5]


# -- telemetry: server publication vs. sidecar ---------------------------------
@pytest.mark.telemetry
class TestHttpServerRaces:
    @pytest.fixture(autouse=True)
    def fresh(self, monkeypatch):
        monkeypatch.delenv(events.ENV_TELEMETRY, raising=False)
        monkeypatch.delenv(events.ENV_TELEMETRY_DIR, raising=False)
        monkeypatch.delenv(http.ENV_TELEMETRY_HTTP, raising=False)
        telemetry.reset()
        yield
        telemetry.reset()

    def test_concurrent_starts_yield_one_server(self, tmp_path):
        barrier = threading.Barrier(4)
        results: list = [None] * 4

        def start(k):
            barrier.wait()
            results[k] = http.start_http_server(
                0, directory=str(tmp_path)
            )

        threads = [
            threading.Thread(target=start, args=(k,)) for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert all(r is not None for r in results)
        assert len({id(r) for r in results}) == 1
        assert http.get_http_server() is results[0]
        http.stop_http_server()

    def test_start_stop_race_never_leaks_a_sidecar(
        self, tmp_path, monkeypatch
    ):
        """2 starters vs. 2 stoppers, with the sidecar write slowed to
        model a stalled telemetry dir (NFS, overloaded disk). Pre-fix,
        the server was published before its sidecar write: a stop could
        swap it out and finish while ``sidecar_path`` was still None,
        after which the write landed an ``http_rank<k>.json`` no stop
        would ever unlink. Distinct ranks per start keep a leaked
        sidecar visible instead of letting the next server overwrite
        (then retract) the same filename."""
        real_write = http.write_port_sidecar

        def slow_write(*args, **kwargs):
            time.sleep(0.75)  # > stop()'s serve_forever poll interval
            return real_write(*args, **kwargs)

        monkeypatch.setattr(http, "write_port_sidecar", slow_write)
        rank_counter = iter(range(10_000))

        for _ in range(2):
            barrier = threading.Barrier(4)
            starters_done = threading.Event()

            def start():
                rank = next(rank_counter)
                barrier.wait()
                http.start_http_server(
                    0, directory=str(tmp_path), rank=rank
                )

            def stop():
                # hammer stop until the starters are through: one of
                # these calls lands inside start's publication window
                barrier.wait()
                while not starters_done.is_set():
                    http.stop_http_server()

            starters = [threading.Thread(target=start) for _ in range(2)]
            threads = starters + [
                threading.Thread(target=stop) for _ in range(2)
            ]
            for t in threads:
                t.start()
            for t in starters:
                t.join(timeout=30)
            starters_done.set()
            for t in threads:
                t.join(timeout=30)
            # retire whichever server survived the race, then nothing may
            # remain: every created server's sidecar dies with it
            http.stop_http_server()
            assert http.get_http_server() is None
            leaked = glob.glob(os.path.join(str(tmp_path), "http_rank*"))
            assert not leaked, leaked
