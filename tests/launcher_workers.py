"""Importable worker functions for launcher tests (the launcher runs
functions by reference — they must live in a real module, which is itself
the Q13-fix behavior under test)."""

import os


def echo_rank(tag="none"):
    return {
        "rank": int(os.environ.get("MLSPARK_PROCESS_ID", "-1")),
        "world": int(os.environ.get("MLSPARK_NUM_PROCESSES", "-1")),
        "master": os.environ.get("MASTER_ADDR"),
        "tag": tag,
    }


def boom():
    raise RuntimeError("worker exploded (intentional)")


def flaky_until(marker_path):
    """Fails the whole gang until the marker exists; every failing rank
    writes it (any single writer could be SIGKILLed by gang teardown before
    its write lands) — exercises the restart path."""
    import os

    if not os.path.exists(marker_path):
        rank = os.environ.get("MLSPARK_PROCESS_ID", "?")
        with open(f"{marker_path}.{rank}", "w") as f:
            f.write("failed once")
        os.replace(f"{marker_path}.{rank}", marker_path)
        raise RuntimeError("flaky failure (intentional)")
    return {"attempt": "recovered"}


def fail_rank(target=1):
    """Exit nonzero on the targeted rank of the CURRENT world; everyone
    else returns their coordinates (plus the elastic env contract). The
    always-failing rank for the elastic-policy tests: once a shrink
    removes it from the world, the gang succeeds."""
    rank = int(os.environ.get("MLSPARK_PROCESS_ID", "0"))
    if rank == int(target):
        raise RuntimeError(f"rank {rank} exploded (injected permanent loss)")
    return {
        "rank": rank,
        "world": int(os.environ.get("MLSPARK_NUM_PROCESSES", "1")),
        "elastic_env": os.environ.get("MLSPARK_ELASTIC"),
    }


def unpicklable_result():
    return lambda: None  # cannot cross the result-file boundary


def sleep_forever():
    """Never returns (but keeps heartbeating) — only the gang deadline
    can end this worker."""
    import time

    while True:
        time.sleep(0.25)


def cross_process_sum():
    """Verifies jax.distributed actually rendezvoused: allgather each rank's
    value and sum — the collective path the reference delegates to gloo."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    rank = jax.process_index()
    world = jax.process_count()
    gathered = multihost_utils.process_allgather(jnp.asarray([rank + 1.0]))
    return {"rank": rank, "world": world, "sum": float(gathered.sum())}


def dp_train_step_parity():
    """Real 2-process DP training: jax.distributed rendezvous, a psum train
    step over a cross-process mesh, replica-sync assertion — the full gloo
    DDP loop (``distributed_multilayer_perceptron.py:122-143``) as compiled
    collectives. Deterministic: the test re-runs the same workload
    single-process and compares losses + the param fingerprint."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from machine_learning_apache_spark_tpu.models import MLP
    from machine_learning_apache_spark_tpu.parallel import make_mesh
    from machine_learning_apache_spark_tpu.parallel.data_parallel import (
        assert_replicas_in_sync,
        make_data_parallel_step,
        params_fingerprint,
    )
    from machine_learning_apache_spark_tpu.parallel.mesh import (
        DATA_AXIS,
        shard_batch,
    )
    from machine_learning_apache_spark_tpu.train.losses import cross_entropy
    from machine_learning_apache_spark_tpu.train.state import (
        TrainState,
        make_optimizer,
    )

    rank, world = jax.process_index(), jax.process_count()
    rng = np.random.default_rng(0)
    feats = rng.normal(size=(16, 4)).astype(np.float32)
    labels = rng.integers(0, 3, 16).astype(np.int64)

    model = MLP(layers=(4, 5, 3))
    params = model.init(jax.random.key(0), jnp.ones((1, 4)))["params"]
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=make_optimizer("sgd", 0.1)
    )
    mesh = make_mesh({DATA_AXIS: world})

    def loss_fn(p, batch, step_rng):
        x, y = batch
        del step_rng
        return cross_entropy(model.apply({"params": p}, x), y), {}

    step = make_data_parallel_step(loss_fn, mesh)
    shard = 16 // world
    local = (
        feats[rank * shard : (rank + 1) * shard],
        labels[rank * shard : (rank + 1) * shard],
    )
    batch = shard_batch(mesh, local)
    losses = []
    for _ in range(3):
        state, loss, _ = step(state, batch, jax.random.key(1))
        losses.append(float(loss))
    divergence = assert_replicas_in_sync(state.params)
    return {
        "rank": rank,
        "world": world,
        "losses": losses,
        "fingerprint": params_fingerprint(state.params),
        "divergence": divergence,
    }


def fault_drill_train(workdir, epochs=4, checkpoint_every=1):
    """Restart-safe training workload for the fault drill: deterministic
    per-rank MLP training with per-rank checkpoint dirs and
    ``fit(resume=True)``. When the gang is killed mid-run (an injected
    crash/stall on one rank) and retried, every rank resumes from its last
    complete checkpoint and the final loss must match an unfaulted run —
    the tentpole's loss-parity acceptance check. Per-rank checkpoint dirs:
    local-process orbax needs no cross-rank coordination, and the drill
    asserts every rank independently recovers."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from machine_learning_apache_spark_tpu.models import MLP
    from machine_learning_apache_spark_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from machine_learning_apache_spark_tpu.train.loop import fit
    from machine_learning_apache_spark_tpu.train.losses import cross_entropy
    from machine_learning_apache_spark_tpu.train.state import (
        TrainState,
        make_optimizer,
    )

    rank = jax.process_index()
    rng = np.random.default_rng(7)
    feats = rng.normal(size=(32, 4)).astype(np.float32)
    labels = rng.integers(0, 3, 32).astype(np.int64)
    loader = [
        (feats[i * 8 : (i + 1) * 8], labels[i * 8 : (i + 1) * 8])
        for i in range(4)
    ]

    model = MLP(layers=(4, 8, 3))
    params = model.init(jax.random.key(0), jnp.ones((1, 4)))["params"]
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=make_optimizer("sgd", 0.1)
    )

    def loss_fn(p, batch, step_rng):
        del step_rng
        x, y = batch
        return cross_entropy(model.apply({"params": p}, x), y), {}

    with CheckpointManager(os.path.join(workdir, f"ckpt_r{rank}")) as ckpt:
        res = fit(
            state, loss_fn, loader,
            epochs=epochs,
            checkpointer=ckpt,
            checkpoint_every=checkpoint_every,
            resume=True,
            log_every=0,
        )
    return {
        "rank": rank,
        "final_loss": res.final_loss,
        "resumed_step": res.resumed_step,
        "epochs_run": len(res.history),
    }


def multihost_probe():
    """Multi-host control-plane probe: prints a parseable line with this
    rank's view of the world plus a cross-process collective sum — consumed
    by the commands_for_hosts end-to-end test, which drives the LITERAL
    launch commands an external scheduler (spark-submit's role,
    ``distributed_cnn.py:227-231``) would execute."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    rank = jax.process_index()
    world = jax.process_count()
    gathered = multihost_utils.process_allgather(jnp.asarray([rank + 1.0]))
    print(
        f"MULTIHOST_RESULT rank={rank} world={world} sum={float(gathered.sum())}",
        flush=True,
    )


def echo_dp_mode():
    """The zero1 env contract as a worker sees it (Distributor(dp_mode=...)
    must plumb MLSPARK_DP_MODE into every rank's environment)."""
    return {
        "dp_mode": os.environ.get("MLSPARK_DP_MODE"),
        "rank": int(os.environ.get("MLSPARK_PROCESS_ID", "-1")),
    }


def echo_ingest_env():
    """The ingest env contract as a worker sees it (Distributor(ingest=...)
    must plumb MLSPARK_INGEST_* into every rank's environment), resolved
    through IngestConfig.from_env exactly as a worker's StreamingPipeline
    would."""
    from machine_learning_apache_spark_tpu.ingest.config import IngestConfig

    cfg = IngestConfig.from_env()
    return {
        "buffer": cfg.buffer,
        "tail": cfg.tail,
        "rank": int(os.environ.get("MLSPARK_PROCESS_ID", "-1")),
    }


def echo_telemetry_http():
    """The observability-plane env contract as a worker sees it
    (Distributor(telemetry_http=...) must plumb MLSPARK_TELEMETRY_HTTP
    into every rank's environment)."""
    return {
        "telemetry_http": os.environ.get("MLSPARK_TELEMETRY_HTTP"),
        "rank": int(os.environ.get("MLSPARK_PROCESS_ID", "-1")),
    }


def elastic_drill_train(workdir, epochs=4, checkpoint_every=1,
                        global_batch=168, steps_per_epoch=2):
    """Elastic-resume workload for the shrink drill: ZeRO-1 training over
    the gang-wide ``data`` mesh with per-rank checkpoint directories and
    ``fit(resume=True)``. Elastic resume itself is resolved through the
    env contract — ``Distributor(elastic=True)`` sets ``MLSPARK_ELASTIC=1``
    — so a shrunken retry reshards the surviving group automatically.

    The default ``global_batch=168 = lcm(8, 7, 6)`` divides every world
    size on the 8 -> 7 -> 6 shrink path: each world slices the SAME
    global rows per step, so the batch schedule (and hence the loss
    trajectory, up to collective reduction order) is world-independent —
    the drill's loss-parity acceptance check depends on it.
    ``bucket_bytes=128`` forces multiple ZeRO-1 buckets, so the reshard
    crosses bucket seams, not just shard boundaries."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from machine_learning_apache_spark_tpu.models import MLP
    from machine_learning_apache_spark_tpu.parallel import make_mesh
    from machine_learning_apache_spark_tpu.parallel.mesh import DATA_AXIS
    from machine_learning_apache_spark_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from machine_learning_apache_spark_tpu.train.loop import fit
    from machine_learning_apache_spark_tpu.train.losses import cross_entropy
    from machine_learning_apache_spark_tpu.train.state import (
        TrainState,
        make_optimizer,
    )

    rank, world = jax.process_index(), jax.process_count()
    if global_batch % world:
        raise ValueError(
            f"global_batch {global_batch} must divide world {world}"
        )
    rng = np.random.default_rng(7)
    n = global_batch * steps_per_epoch
    feats = rng.normal(size=(n, 4)).astype(np.float32)
    labels = rng.integers(0, 3, n).astype(np.int64)
    per = global_batch // world
    loader = []
    for s in range(steps_per_epoch):
        rows = slice(s * global_batch, (s + 1) * global_batch)
        gx, gy = feats[rows], labels[rows]
        loader.append(
            (gx[rank * per:(rank + 1) * per], gy[rank * per:(rank + 1) * per])
        )

    model = MLP(layers=(4, 8, 3))
    params = model.init(jax.random.key(0), jnp.ones((1, 4)))["params"]
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=make_optimizer("adam", 0.05)
    )

    def loss_fn(p, batch, step_rng):
        del step_rng
        x, y = batch
        return cross_entropy(model.apply({"params": p}, x), y), {}

    mesh = make_mesh({DATA_AXIS: world})
    with CheckpointManager(os.path.join(workdir, f"ckpt_r{rank}")) as ckpt:
        res = fit(
            state, loss_fn, loader,
            epochs=epochs,
            mesh=mesh,
            dp_mode="zero1",
            dp_bucket_bytes=128,
            checkpointer=ckpt,
            checkpoint_every=checkpoint_every,
            resume=True,
            log_every=0,
        )
    return {
        "rank": rank,
        "world": world,
        "final_loss": res.final_loss,
        "resumed_step": res.resumed_step,
        "epochs_run": len(res.history),
    }
