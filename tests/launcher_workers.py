"""Importable worker functions for launcher tests (the launcher runs
functions by reference — they must live in a real module, which is itself
the Q13-fix behavior under test)."""

import os


def echo_rank(tag="none"):
    return {
        "rank": int(os.environ.get("MLSPARK_PROCESS_ID", "-1")),
        "world": int(os.environ.get("MLSPARK_NUM_PROCESSES", "-1")),
        "master": os.environ.get("MASTER_ADDR"),
        "tag": tag,
    }


def boom():
    raise RuntimeError("worker exploded (intentional)")


def flaky_until(marker_path):
    """Fails the whole gang until the marker exists; every failing rank
    writes it (any single writer could be SIGKILLed by gang teardown before
    its write lands) — exercises the restart path."""
    import os

    if not os.path.exists(marker_path):
        rank = os.environ.get("MLSPARK_PROCESS_ID", "?")
        with open(f"{marker_path}.{rank}", "w") as f:
            f.write("failed once")
        os.replace(f"{marker_path}.{rank}", marker_path)
        raise RuntimeError("flaky failure (intentional)")
    return {"attempt": "recovered"}


def unpicklable_result():
    return lambda: None  # cannot cross the result-file boundary


def cross_process_sum():
    """Verifies jax.distributed actually rendezvoused: allgather each rank's
    value and sum — the collective path the reference delegates to gloo."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    rank = jax.process_index()
    world = jax.process_count()
    gathered = multihost_utils.process_allgather(jnp.asarray([rank + 1.0]))
    return {"rank": rank, "world": world, "sum": float(gathered.sum())}
