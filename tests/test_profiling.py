"""Profiling hook tests: device traces actually land on disk, the step-window
tracer opens/closes correctly, and fit()'s profile_dir integration works."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from machine_learning_apache_spark_tpu.utils.profiling import (
    StepWindowTracer,
    annotate,
    device_trace,
)


def trace_files(log_dir: str) -> list[str]:
    return glob.glob(
        os.path.join(log_dir, "**", "*.xplane.pb"), recursive=True
    )


class TestDeviceTrace:
    def test_trace_written(self, tmp_path):
        d = str(tmp_path / "trace")
        with device_trace(d):
            with annotate("square"):
                jax.jit(lambda x: x * x)(jnp.arange(8.0)).block_until_ready()
        assert trace_files(d), "no xplane trace written"


class TestStepWindowTracer:
    def test_window(self, tmp_path):
        d = str(tmp_path / "w")
        t = StepWindowTracer(d, start=1, stop=3)
        for step in range(5):
            t.on_step(step)
            jnp.square(jnp.arange(4.0)).block_until_ready()
        assert not t._active  # closed at step 3
        assert trace_files(d)

    def test_stride_crosses_window(self, tmp_path):
        # A stride-K caller (fit's steps_per_call) can jump the counter
        # straight over [start, stop): the tracer must still capture at
        # least one dispatch, and must not restart after closing.
        d = str(tmp_path / "stride")
        t = StepWindowTracer(d, start=2, stop=5)
        for step in (0, 5, 10, 15):
            t.on_step(step)
            jnp.square(jnp.arange(4.0)).block_until_ready()
        t.close()
        assert not t._active and t._done
        assert trace_files(d)

    def test_stride_enters_and_leaves(self, tmp_path):
        d = str(tmp_path / "stride2")
        t = StepWindowTracer(d, start=2, stop=5)
        for step in (0, 4, 8, 12):  # enters at 4, leaves at 8
            t.on_step(step)
            jnp.square(jnp.arange(4.0)).block_until_ready()
        assert not t._active and t._done  # closed at 8, no restart at 12
        assert trace_files(d)

    def test_none_dir_noop(self):
        t = StepWindowTracer(None)
        for step in range(10):
            t.on_step(step)
        t.close()

    def test_close_mid_window(self, tmp_path):
        d = str(tmp_path / "mid")
        t = StepWindowTracer(d, start=0, stop=100)
        t.on_step(0)
        jnp.square(jnp.arange(4.0)).block_until_ready()
        t.close()
        assert trace_files(d)

    def test_exception_mid_window_stops_profiler(self, tmp_path):
        """fit() failing inside the trace window must stop the process-global
        profiler so later traces can start."""
        from machine_learning_apache_spark_tpu.train.loop import fit
        from machine_learning_apache_spark_tpu.train.state import (
            TrainState,
            make_optimizer,
        )
        from machine_learning_apache_spark_tpu.models import MLP

        model = MLP((4, 8, 3))
        params = model.init(jax.random.key(0), jnp.ones((1, 4)))["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer("sgd", 0.1)
        )

        def bad_loss(params, batch, rng):
            raise RuntimeError("boom")

        batches = [(np.ones((4, 4), np.float32), np.zeros(4, np.int64))] * 4
        with pytest.raises(RuntimeError, match="boom"):
            fit(
                state, bad_loss, batches, epochs=1, log_every=0,
                profile_dir=str(tmp_path / "t"), profile_window=(0, 100),
            )
        # profiler must be stopped: a fresh trace can start
        with device_trace(str(tmp_path / "t2")):
            jnp.square(jnp.arange(4.0)).block_until_ready()
        assert trace_files(str(tmp_path / "t2"))

    def test_empty_window_rejected(self):
        with pytest.raises(ValueError):
            StepWindowTracer("/tmp/x", start=5, stop=5)


class TestFitIntegration:
    def test_fit_profile_dir(self, tmp_path):
        from machine_learning_apache_spark_tpu.data import ArrayDataset, DataLoader
        from machine_learning_apache_spark_tpu.models import MLP
        from machine_learning_apache_spark_tpu.train.loop import (
            classification_loss,
            fit,
        )
        from machine_learning_apache_spark_tpu.train.state import (
            TrainState,
            make_optimizer,
        )

        model = MLP((4, 8, 3))
        ds = ArrayDataset(
            np.random.default_rng(0).normal(size=(64, 4)).astype(np.float32),
            np.zeros(64, dtype=np.int64),
        )
        state = TrainState.create(
            apply_fn=model.apply,
            params=model.init(jax.random.key(0), ds[:1][0])["params"],
            tx=make_optimizer("sgd", 0.03),
        )
        d = str(tmp_path / "fit_trace")
        fit(
            state,
            classification_loss(model.apply),
            DataLoader(ds, 16),
            epochs=2,
            log_every=0,
            profile_dir=d,
            profile_window=(1, 3),
        )
        assert trace_files(d)
