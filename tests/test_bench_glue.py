"""bench.py main() stage glue, executed with stubbed workloads.

The scanned/packed/sweep stages are TPU-gated, so their GLUE (deadline/
retry wrappers, result merging, quarantine propagation) never runs in CPU
smoke runs — a NameError there would first surface on the driver's
end-of-round TPU run, which is exactly the artifact that must never be
lost. These tests open the gate (BENCH_FORCE_TPU_STAGES) and drive main()
with canned workload results, so every glue path executes in milliseconds.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


MT = {
    "median": 600000.0, "max": 620000.0, "trials": [600000.0],
    "spread": 1.03, "steps_per_trial": 240, "scan_k": 1,
    "flops_per_step": 4.2e11, "achieved_flops_per_sec_chip": 4e13,
    "mfu": 0.21, "device": "TPU v5 lite", "n_chips": 1,
    "batch_per_chip": 32, "layers": 1, "loss": 1.0,
    "paired_window": {"steady_state_rate": 700000.0},
}
CNN = {
    "value": 1000000.0, "unit": "samples/sec/chip", "median": 1000000.0,
    "max": 1.1e6, "trials": [1e6], "spread": 1.1, "steps_per_trial": 2000,
    "scan_k": 50, "mfu": 0.03, "batch_per_chip": 512,
}
PACKED = {
    "pairs_per_sec_chip": 30000.0, "max": 31000.0, "spread": 1.03,
    "pairs_per_row": 11.5, "token_efficiency": 0.89,
    "unpacked_token_efficiency": 0.08, "loss": 2.0,
}
COMPOSED = {
    "pairs_per_sec_chip": 90000.0, "max": 95000.0, "spread": 1.05,
    "grid_tokens_per_sec_chip": 1.6e6, "effective_tokens_per_sec_chip": 1.4e6,
    "mfu": 0.35, "batch_per_chip": 512, "scan_k": 4, "steps_per_trial": 20,
    "pairs_per_row": 11.5, "token_efficiency": 0.88, "loss": 1.5,
}


@pytest.fixture
def stage_env(monkeypatch):
    # Keep main() from enabling the persistent XLA compilation cache:
    # every workload here is stubbed so the cache does nothing for these
    # tests, but the config it flips is process-global and serializing
    # later CPU compiles through it segfaults jaxlib 0.4.37 (observed on
    # test_checkpoint's TP/EP program when run after this file).
    monkeypatch.setenv("BENCH_COMPILE_CACHE", "0")
    monkeypatch.setenv("BENCH_FORCE_TPU_STAGES", "1")
    monkeypatch.setenv("BENCH_PLATFORM", "cpu")
    monkeypatch.setattr(bench, "bench_torch_transformer", lambda: 1200.0)
    monkeypatch.setattr(bench, "bench_torch_cnn", lambda: 3000.0)
    monkeypatch.setattr(bench, "bench_cnn", lambda jax: dict(CNN))
    monkeypatch.setattr(bench, "bench_composed", lambda jax, **kw: dict(COMPOSED))
    return monkeypatch


def _run_main(capsys):
    bench.main()
    # The artifact contract: stdout is EXACTLY one JSON line (package
    # loggers are rerouted to stderr by _init_backend; a stray log line
    # here is a driver-facing regression).
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1, f"stdout must be one JSON line, got {lines}"
    return json.loads(lines[0])


def test_all_stages_merge(stage_env, capsys):
    stage_env.setattr(
        bench, "bench_transformer", lambda jax, **kw: dict(MT)
    )
    stage_env.setattr(
        bench, "bench_packed_transformer", lambda jax, **kw: dict(PACKED)
    )
    stage_env.setattr(
        bench, "bench_transformer_sweep",
        lambda jax, points=None, stop_at=None: [
            {"batch_per_chip": 128, "layers": 1, "tokens_per_sec_chip": 7e5}
        ],
    )
    out = _run_main(capsys)
    assert out["value"] == 600000.0
    assert out["vs_baseline"] == 500.0
    assert out["scanned"]["median"] == 600000.0  # sliced keys present
    assert out["packed"]["pairs_per_sec_chip"] == 30000.0
    # 600000/200 = 3000 pairs/s unpacked ceiling → 10x
    assert out["packed"]["vs_unpacked_pairs_rate"] == 10.0
    assert out["composed"]["pairs_per_sec_chip"] == 90000.0
    assert out["sweep"][0]["batch_per_chip"] == 128
    assert out["cnn"]["vs_baseline"] == round(1000000.0 / 3000.0, 3)
    assert "after_timeout" not in out["cnn"]


def test_headline_timeout_quarantines_later_stages(stage_env, capsys):
    def hung(jax, **kw):
        raise TimeoutError("transformer deadline (900s) exceeded")

    stage_env.setattr(bench, "bench_transformer", hung)
    called = {"packed": 0, "sweep": 0, "composed": 0}
    stage_env.setattr(
        bench, "bench_packed_transformer",
        lambda jax, **kw: called.__setitem__("packed", 1) or dict(PACKED),
    )
    stage_env.setattr(
        bench, "bench_composed",
        lambda jax, **kw: called.__setitem__("composed", 1) or dict(COMPOSED),
    )
    stage_env.setattr(
        bench, "bench_transformer_sweep",
        lambda jax, points=None, stop_at=None: called.__setitem__("sweep", 1) or [],
    )
    out = _run_main(capsys)
    assert "TimeoutError" in out["error"]
    assert called == {"packed": 0, "sweep": 0, "composed": 0}  # skipped
    assert "scanned" not in out
    # CNN kept for artifact completeness but flagged untrustworthy.
    assert out["cnn"]["after_timeout"] is True


def test_cpu_fallback_embeds_tpu_evidence(stage_env, capsys):
    """VERDICT r04 item 2: a dead tunnel at driver time must not produce an
    artifact with zero TPU numbers — the committed record rides along."""
    stage_env.setattr(bench, "bench_transformer", lambda jax, **kw: dict(MT))
    stage_env.setattr(
        bench, "bench_packed_transformer", lambda jax, **kw: dict(PACKED)
    )
    stage_env.setattr(
        bench, "bench_transformer_sweep",
        lambda jax, points=None, stop_at=None: [],
    )
    out = _run_main(capsys)
    ev = out["tpu_evidence"]
    assert ev["captured"]  # capture-dated, never passed off as live
    assert ev["transformer"]["median_tokens_per_sec_chip"] > 0
    # mfu may legitimately be None (unknown device kind) — just present.
    assert "mfu" in ev["transformer"]


def test_record_tpu_evidence_roundtrip(tmp_path, monkeypatch):
    """An on-chip run refreshes the committed record with every stage that
    succeeded, and a subsequent load returns it."""
    monkeypatch.setattr(bench, "_EVIDENCE_PATH", str(tmp_path / "ev.json"))
    result = dict(MT)
    result["scanned"] = {"median": 900000.0, "scan_k": 8}
    result["packed"] = dict(PACKED)
    result["composed"] = dict(COMPOSED)
    result["cnn"] = dict(CNN)
    bench._record_tpu_evidence(result)
    ev = bench._load_tpu_evidence()
    assert ev["composed"]["pairs_per_sec_chip"] == 90000.0
    assert ev["transformer"]["median_tokens_per_sec_chip"] == 600000.0
    assert ev["transformer"]["paired_window_steady_state"][
        "tokens_per_sec_chip"
    ] == 700000.0
    assert ev["scanned"]["median"] == 900000.0
    assert ev["packed"]["pairs_per_sec_chip"] == 30000.0
    assert ev["cnn_scanned"]["median_samples_per_sec_chip"] == 1000000.0


def test_record_merges_per_stage(tmp_path, monkeypatch):
    """A partial run must not erase the last good number for stages it
    didn't measure: transformer-only then cnn-only leaves both on record,
    with per-stage capture dates."""
    monkeypatch.setattr(bench, "_EVIDENCE_PATH", str(tmp_path / "ev.json"))
    bench._record_tpu_evidence(dict(MT))
    cnn_only = {"cnn": dict(CNN)}
    bench._record_tpu_evidence(cnn_only)
    ev = bench._load_tpu_evidence()
    assert ev["transformer"]["median_tokens_per_sec_chip"] == 600000.0
    assert ev["cnn_scanned"]["median_samples_per_sec_chip"] == 1000000.0
    assert set(ev["stage_captured"]) == {"transformer", "cnn_scanned"}


def test_record_skips_failed_stages(tmp_path, monkeypatch):
    """A failed stage must not overwrite the record with an error dict; a
    sweep banks its CLEAN rows only (error/truncated rows cost that point,
    never the survivors); a run where nothing succeeded leaves the old
    record untouched."""
    path = tmp_path / "ev.json"
    monkeypatch.setattr(bench, "_EVIDENCE_PATH", str(path))
    ok = dict(MT)
    ok["packed"] = {"error": "TimeoutError(...)"}
    ok["composed"] = {"skipped": "total budget"}
    ok["sweep"] = [{"batch_per_chip": 128, "layers": 1}]  # salvage list...
    ok["sweep_error"] = "ValueError('mid-sweep crash')"  # ...from a crash
    bench._record_tpu_evidence(ok)
    ev = bench._load_tpu_evidence()
    assert "packed" not in ev
    assert "composed" not in ev  # budget skip is not a measurement
    # The clean salvage row banks even though the sweep as a whole crashed.
    assert ev["sweep"] == [{"batch_per_chip": 128, "layers": 1}]
    skip_sweep = dict(MT)
    skip_sweep["sweep"] = {"skipped": "total budget"}
    bench._record_tpu_evidence(skip_sweep)
    assert bench._load_tpu_evidence()["sweep"] == [
        {"batch_per_chip": 128, "layers": 1}
    ]  # a deliberate skip banks nothing and erases nothing
    # A truncated sweep's clean rows merge per config (newest wins); the
    # sentinel itself never lands in the record.
    trunc = dict(MT)
    trunc["sweep"] = [
        {"batch_per_chip": 128, "layers": 1, "mfu": 0.1},
        {"truncated": "time budget"},
    ]
    bench._record_tpu_evidence(trunc)
    ev = bench._load_tpu_evidence()
    assert ev["sweep"] == [{"batch_per_chip": 128, "layers": 1, "mfu": 0.1}]
    before = path.read_text()
    bench._record_tpu_evidence({"error": "boom", "cnn": {"error": "x"}})
    assert path.read_text() == before  # nothing measured → keep old record


def test_total_budget_skips_optional_stages_keeps_cnn(stage_env, capsys):
    """With the total-run ledger exhausted, optional stages are recorded as
    skipped (not silently absent, never stamped into the evidence record)
    while the headline and CNN still run — a partial artifact always beats
    none."""
    stage_env.setenv("BENCH_TOTAL_BUDGET", "0")
    called = {"scanned": 0}

    def mt(jax, **kw):
        if kw.get("scan_k"):
            called["scanned"] = 1
        return dict(MT)

    stage_env.setattr(bench, "bench_transformer", mt)
    stage_env.setattr(
        bench, "bench_packed_transformer", lambda jax, **kw: dict(PACKED)
    )
    stage_env.setattr(
        bench, "bench_transformer_sweep",
        lambda jax, points=None, stop_at=None: [],
    )
    out = _run_main(capsys)
    assert out["value"] == 600000.0  # headline still ran (its own deadline)
    assert out["scanned"] == {"skipped": "total budget"}
    assert called["scanned"] == 0
    assert out["packed"] == {"skipped": "total budget"}
    assert out["composed"] == {"skipped": "total budget"}
    assert out["sweep"] == {"skipped": "total budget"}
    assert "sweep_error" not in out  # a deliberate skip is not a failure
    assert out["cnn"]["value"] == 1000000.0  # reserve spent on the CNN


def test_stage_failure_does_not_void_others(stage_env, capsys):
    stage_env.setattr(
        bench, "bench_transformer", lambda jax, **kw: dict(MT)
    )
    stage_env.setattr(
        bench, "bench_packed_transformer",
        lambda jax, **kw: (_ for _ in ()).throw(ValueError("boom")),
    )
    stage_env.setattr(
        bench, "bench_transformer_sweep",
        lambda jax, points=None, stop_at=None: [],
    )
    out = _run_main(capsys)
    assert out["value"] == 600000.0  # headline intact
    assert "error" in out["packed"]
    assert "sweep" in out  # non-timeout failure does not quarantine
    assert "after_timeout" not in out["cnn"]


def test_record_merges_sweep_rows_per_config(tmp_path, monkeypatch):
    """A BENCH_SWEEP_POINTS-restricted re-capture (e.g. just the L=4 rows
    a hang stole) must merge into the recorded sweep per (batch, layers),
    not replace it — the rows that landed in an earlier window survive."""
    monkeypatch.setattr(bench, "_EVIDENCE_PATH", str(tmp_path / "ev.json"))
    first = dict(MT)
    first["sweep"] = [
        {"batch_per_chip": 128, "layers": 1, "mfu": 0.18},
        {"batch_per_chip": 32, "layers": 4, "mfu": 0.10},
    ]
    bench._record_tpu_evidence(first)
    second = dict(MT)
    second["sweep"] = [{"batch_per_chip": 32, "layers": 4, "mfu": 0.25}]
    bench._record_tpu_evidence(second)
    ev = bench._load_tpu_evidence()
    rows = {(p["batch_per_chip"], p["layers"]): p["mfu"] for p in ev["sweep"]}
    assert rows == {(128, 1): 0.18, (32, 4): 0.25}


def test_sweep_points_env_restricts_plan(monkeypatch):
    """BENCH_SWEEP_POINTS runs exactly the named (batch x layers) points —
    scarce tunnel windows must not re-measure rows that already landed."""
    monkeypatch.setenv("BENCH_SWEEP_POINTS", "32x4,128X4")
    ran = []

    def fake_bench_transformer(jax, batch_per_chip=None, layers=None, **kw):
        ran.append((batch_per_chip, layers))
        return {
            "median": 1.0, "mfu": 0.1, "spread": 1.0, "paired_window": {},
        }

    monkeypatch.setattr(bench, "bench_transformer", fake_bench_transformer)
    points = bench.bench_transformer_sweep(jax=None)
    assert ran == [(32, 4), (128, 4)]
    assert [(p["batch_per_chip"], p["layers"]) for p in points] == ran


def test_sweep_isolated_point_records_child_result(monkeypatch):
    """BENCH_SWEEP_ISOLATE=1 runs each point via _run_point_isolated: the
    child's LAST stdout line is the point's bench_transformer dict (earlier
    lines are logging noise and must be ignored)."""
    monkeypatch.setenv("BENCH_SWEEP_ISOLATE", "1")
    monkeypatch.setenv("BENCH_SWEEP_POINTS", "32x4,128x4")
    payload = json.dumps(
        {"median": 2.0, "mfu": 0.2, "spread": 1.0, "paired_window": {}}
    )
    monkeypatch.setattr(
        bench, "_sweep_point_cmd",
        lambda bpc, layers: [
            sys.executable, "-c", f"print('noise'); print({payload!r})",
        ],
    )
    points = bench.bench_transformer_sweep(jax=None)
    assert [(p["batch_per_chip"], p["layers"]) for p in points] == [
        (32, 4), (128, 4),
    ]
    assert all(p["mfu"] == 0.2 for p in points)


def test_sweep_isolated_hang_is_one_row_not_a_truncation(monkeypatch):
    """The r05 failure mode, fixed: a hung point under isolation is killed
    at BENCH_SWEEP_POINT_DEADLINE, costs ONE {"error": ...} row, and the
    NEXT point still runs — no {"truncated": "hung point"} quarantine,
    because the wedge died with its own process."""
    monkeypatch.setenv("BENCH_SWEEP_ISOLATE", "1")
    monkeypatch.setenv("BENCH_SWEEP_POINTS", "32x4,128x4")
    monkeypatch.setenv("BENCH_SWEEP_POINT_DEADLINE", "1")
    payload = json.dumps(
        {"median": 2.0, "mfu": 0.2, "spread": 1.0, "paired_window": {}}
    )

    def cmd(bpc, layers):
        if bpc == 32:  # first point hangs past the 1s deadline
            return [sys.executable, "-c", "import time; time.sleep(60)"]
        return [sys.executable, "-c", f"print({payload!r})"]

    monkeypatch.setattr(bench, "_sweep_point_cmd", cmd)
    points = bench.bench_transformer_sweep(jax=None)
    assert len(points) == 2
    assert points[0]["isolated"] and "TimeoutError" in points[0]["error"]
    assert "truncated" not in points[0] and "truncated" not in points[1]
    assert points[1]["mfu"] == 0.2


def test_sweep_isolated_child_crash_costs_that_point_only(monkeypatch):
    """A child that exits nonzero (OOM, import error) is an error row with
    the stderr tail attached; the sweep moves on."""
    monkeypatch.setenv("BENCH_SWEEP_ISOLATE", "1")
    monkeypatch.setenv("BENCH_SWEEP_POINTS", "32x4,128x4")
    payload = json.dumps(
        {"median": 2.0, "mfu": 0.2, "spread": 1.0, "paired_window": {}}
    )

    def cmd(bpc, layers):
        if bpc == 32:
            return [
                sys.executable, "-c",
                "import sys; print('boom', file=sys.stderr); sys.exit(3)",
            ]
        return [sys.executable, "-c", f"print({payload!r})"]

    monkeypatch.setattr(bench, "_sweep_point_cmd", cmd)
    points = bench.bench_transformer_sweep(jax=None)
    assert len(points) == 2
    assert points[0]["isolated"] and "boom" in points[0]["error"]
    assert points[1]["mfu"] == 0.2
