"""Length-bucketing tests: bucket assignment, per-bucket shapes, epoch
shuffling, padding-efficiency gain over fixed-length padding (the SURVEY.md
§7 'ragged text batching' hard part)."""

import numpy as np
import pytest

from machine_learning_apache_spark_tpu.data.bucketing import (
    BucketByLengthLoader,
    assign_buckets,
)
from machine_learning_apache_spark_tpu.data.datasets import (
    synthetic_text_classification,
)
from machine_learning_apache_spark_tpu.data.text import (
    PAD_ID,
    classification_pipeline,
)


class TestAssignBuckets:
    def test_boundaries(self):
        out = assign_buckets(np.array([1, 32, 33, 64, 100, 500]), (32, 64, 128))
        np.testing.assert_array_equal(out, [0, 0, 1, 1, 2, 2])


class TestLoader:
    def make(self, n=400, **kw):
        texts, labels = synthetic_text_classification(n, max_len=30)
        pipe = classification_pipeline(texts, max_seq_len=64)
        ragged = pipe.ragged(texts)
        defaults = dict(batch_size=16, boundaries=(12, 20, 34), seed=3)
        defaults.update(kw)
        return BucketByLengthLoader(ragged, labels, **defaults), ragged, labels

    def test_shapes_are_bucket_boundaries(self):
        loader, ragged, _ = self.make()
        widths = set()
        for ids, lbls in loader:
            assert ids.shape[0] == 16 and lbls.shape == (16,)
            widths.add(ids.shape[1])
        assert widths <= {12, 20, 34} and len(widths) >= 2

    def test_content_preserved(self):
        loader, ragged, labels = self.make(shuffle=False)
        seen = 0
        for ids, lbls in loader:
            for row, lbl in zip(ids, lbls):
                # row must equal some source sequence (padded)
                nonpad = row[row != PAD_ID].tolist()
                src = [i for i in np.flatnonzero(labels == lbl)
                       if ragged[i][: ids.shape[1]] == nonpad]
                assert src, "padded row does not match any source sequence"
                seen += 1
        assert seen > 0

    def test_epoch_reshuffles(self):
        loader, _, _ = self.make()
        first = [ids.shape[1] for ids, _ in loader]
        loader.set_epoch(1)
        second = [ids.shape[1] for ids, _ in loader]
        assert len(first) == len(second) == len(loader)
        assert first != second  # interleaving order changed

    def test_efficiency_beats_fixed_padding(self):
        loader, ragged, _ = self.make()
        fixed_width = max(len(s) for s in ragged)
        fixed_eff = sum(len(s) for s in ragged) / (len(ragged) * fixed_width)
        assert loader.padding_efficiency > fixed_eff
        assert loader.padding_efficiency > 0.7

    def test_mismatched_extras_rejected(self):
        with pytest.raises(ValueError, match="extra array"):
            BucketByLengthLoader(
                [[1, 2]], np.zeros(5), batch_size=1, boundaries=(8,)
            )

    def test_overlong_rejected_by_default(self):
        """Silent clipping (which would drop eos) is an error unless opted
        into — the TextPipeline fixed_len guard's analogue."""
        with pytest.raises(ValueError, match="truncate_overlong"):
            BucketByLengthLoader(
                [list(range(1, 50))], batch_size=1, boundaries=(8, 16)
            )

    def test_overlong_truncated_when_opted_in(self):
        loader = BucketByLengthLoader(
            [list(range(1, 50))] * 4, batch_size=2, boundaries=(8, 16),
            shuffle=False, truncate_overlong=True,
        )
        for (ids,) in loader:
            assert ids.shape[1] == 16
            np.testing.assert_array_equal(ids[0], np.arange(1, 17))

    def test_rank_sharding_disjoint_and_complete(self):
        """Two ranks with the same seed cover every example exactly once
        per epoch (the DistributedSampler contract)."""
        seqs = [[7] * (4 + i % 9) for i in range(120)]
        tags = np.arange(120)
        rows = {0: set(), 1: set()}
        for rank in (0, 1):
            loader = BucketByLengthLoader(
                seqs, tags, batch_size=4, boundaries=(8, 16),
                num_replicas=2, rank=rank, drop_last=False, seed=5,
            )
            for _, t in loader:
                rows[rank].update(t.tolist())
        assert rows[0] & rows[1] == set()
        assert rows[0] | rows[1] == set(range(120))

    def test_rank_batch_counts_equal_with_odd_bucket(self):
        """Uneven buckets wrap-pad so every rank yields the SAME number of
        batches — the equal-count invariant collectives depend on."""
        seqs = [[7] * 5 for _ in range(11)]  # one bucket, 11 members
        lens = []
        for rank in (0, 1):
            loader = BucketByLengthLoader(
                seqs, batch_size=2, boundaries=(8,),
                num_replicas=2, rank=rank, seed=1,
            )
            lens.append((len(loader), sum(1 for _ in loader)))
        assert lens[0] == lens[1]
        assert lens[0][0] == lens[0][1] == 3  # ceil(11/2)=6 → 3 batches


class TestPairsLoader:
    """BucketByLengthPairsLoader — paired src/trg bucketing for MT
    (SURVEY.md §7: bucket by length to not waste pod FLOPs)."""

    def _make(self, **kw):
        from machine_learning_apache_spark_tpu.data.bucketing import (
            BucketByLengthPairsLoader,
        )

        rng = np.random.default_rng(0)
        src = [[5] * int(n) for n in rng.integers(3, 30, 64)]
        trg = [[1] + [6] * int(n) + [2] for n in rng.integers(2, 28, 64)]
        kw.setdefault("batch_size", 8)
        kw.setdefault("boundaries", (8, 16, 32))
        return BucketByLengthPairsLoader(src, trg, **kw), src, trg

    def test_shapes_and_bucket_key(self):
        loader, src, trg = self._make(shuffle=False)
        seen = set()
        for s, t in loader:
            assert t.shape[1] == s.shape[1] + 1  # trg one wider (sos shift)
            assert s.shape[1] in (8, 16, 32)
            seen.add(s.shape[1])
        assert len(seen) > 1  # multiple buckets actually exercised

    def test_nothing_silently_clipped(self):
        """Every padded row keeps ALL its real tokens — a bucketing-key
        regression that put a long pair in a short bucket would clip."""
        loader, src, trg = self._make(shuffle=False, drop_last=False)
        for b, idx in loader._schedule(0):
            width = loader.boundaries[b]
            s = loader._pad(idx, width)
            t = loader._pad_trg(idx, width + 1)
            for row_s, row_t, i in zip(s, t, idx):
                # src rows are all-5s, trg all non-zero: non-pad count must
                # equal the original length
                assert int((row_s != 0).sum()) == len(src[i])
                assert int((row_t != 0).sum()) == len(trg[i])

    def test_pair_buckets_by_max_stream(self):
        from machine_learning_apache_spark_tpu.data.bucketing import (
            BucketByLengthPairsLoader,
        )

        # short src, long trg: the PAIR must land in the bucket fitting trg
        src = [[5, 5]] * 8
        trg = [[1] + [6] * 20 + [2]] * 8  # len 22 → key 21 → bucket 32
        loader = BucketByLengthPairsLoader(
            src, trg, batch_size=8, boundaries=(8, 16, 32), shuffle=False
        )
        (s, t), = list(loader)
        assert s.shape == (8, 32) and t.shape == (8, 33)

    def test_length_mismatch_raises(self):
        from machine_learning_apache_spark_tpu.data.bucketing import (
            BucketByLengthPairsLoader,
        )

        with pytest.raises(ValueError, match="src vs"):
            BucketByLengthPairsLoader(
                [[1]], [[1], [2]], batch_size=1, boundaries=(8,)
            )

    def test_padding_efficiency_counts_both_streams(self):
        loader, src, trg = self._make(shuffle=False, drop_last=False)
        eff = loader.padding_efficiency
        assert 0.0 < eff < 1.0
