"""telemetry/: event log, trace spans, metrics registry, gang aggregation,
and the crash flight recorder (docs/OBSERVABILITY.md).

Unit tests drive each surface directly; the aggregation tests build a
synthetic 2-rank gang from hand-written JSONL (deterministic durations,
so the skew report's straggler attribution is exact) and the CLI test
runs ``tools/telemetry_report.py`` against that fixture end to end.
Disabled-mode tests pin the zero-cost contract: module-level no-op
singletons, nothing written, nothing stored.
"""

import json
import os
import subprocess
import sys

import pytest

from machine_learning_apache_spark_tpu import telemetry
from machine_learning_apache_spark_tpu.telemetry import (
    aggregate,
    events,
    http,
    recorder,
    registry,
    spans,
    tracectx,
    traceview,
)

pytestmark = pytest.mark.telemetry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fresh_telemetry(monkeypatch):
    """Every test gets a clean process-global log/registry and no env
    overrides; state is re-armed afterwards so other suites see their own
    environment, not this test's."""
    monkeypatch.delenv(events.ENV_TELEMETRY, raising=False)
    monkeypatch.delenv(events.ENV_TELEMETRY_DIR, raising=False)
    monkeypatch.delenv(events.ENV_MAX_EVENTS, raising=False)
    monkeypatch.delenv(http.ENV_TELEMETRY_HTTP, raising=False)
    monkeypatch.delenv(tracectx.ENV_TRACE, raising=False)
    monkeypatch.delenv(tracectx.ENV_TRACE_SAMPLE, raising=False)
    monkeypatch.delenv("MLSPARK_PROCESS_ID", raising=False)
    telemetry.reset()
    yield
    telemetry.reset()


# -- spans ---------------------------------------------------------------------


class TestSpans:
    def test_nesting_parent_attribution_and_timestamps(self):
        with telemetry.span("outer") as outer:
            assert spans.current_span_id() == outer.id
            with telemetry.span("inner", step=3) as inner:
                assert spans.current_span_id() == inner.id
            assert spans.current_span_id() == outer.id
        assert spans.current_span_id() is None

        evs = events.get_log().snapshot()
        assert [(e.kind, e.name) for e in evs] == [
            ("span_start", "outer"),
            ("span_start", "inner"),
            ("span_end", "inner"),
            ("span_end", "outer"),
        ]
        start_inner, end_inner, end_outer = evs[1], evs[2], evs[3]
        assert start_inner.span == inner.id
        assert start_inner.parent == outer.id
        assert start_inner.attrs == {"step": 3}
        assert end_inner.value is not None and end_inner.value >= 0
        assert end_outer.value >= end_inner.value  # outer encloses inner
        ts = [e.ts for e in evs]
        assert ts == sorted(ts)  # monotonic within a process
        assert all(e.wall > 0 and e.pid == os.getpid() for e in evs)

    def test_exception_tagged_on_span_end(self):
        with pytest.raises(RuntimeError):
            with telemetry.span("boom"):
                raise RuntimeError("x")
        end = events.get_log().snapshot()[-1]
        assert end.kind == "span_end" and end.name == "boom"
        assert end.attrs["error"] == "RuntimeError"
        assert spans.current_span_id() is None  # stack unwound

    def test_leaked_inner_span_does_not_corrupt_stack(self):
        outer = telemetry.span("outer")
        outer.__enter__()
        spans._Span("leaked", None).__enter__()  # never exited
        outer.__exit__(None, None, None)
        assert spans.current_span_id() is None

    def test_traced_decorator(self):
        @spans.traced("my.fn")
        def f(x):
            return x + 1

        assert f(1) == 2
        names = [e.name for e in events.get_log().snapshot()]
        assert names == ["my.fn", "my.fn"]

    def test_per_thread_stacks(self):
        import threading

        got = {}

        def other():
            got["id"] = spans.current_span_id()

        with telemetry.span("main-only"):
            t = threading.Thread(target=other)
            t.start()
            t.join()
        assert got["id"] is None  # spans never leak across threads


# -- event log -----------------------------------------------------------------


class TestEventLog:
    def test_ring_eviction_counts_drops(self):
        log = events.EventLog(max_events=4)
        for i in range(6):
            log.emit("annotation", f"a{i}")
        assert len(log) == 4 and log.dropped == 2
        assert [e.name for e in log.snapshot()] == ["a2", "a3", "a4", "a5"]
        assert [e.name for e in log.tail(2)] == ["a4", "a5"]
        log.clear()
        assert len(log) == 0 and log.dropped == 0

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown event kind"):
            events.EventLog().emit("bogus", "x")

    def test_jsonl_round_trip_and_torn_tail(self, tmp_path):
        log = events.EventLog()
        log.emit("annotation", "a", attrs={"k": 1})
        log.emit("counter", "c", value=2.0)
        path = str(tmp_path / "out.jsonl")
        assert log.export_jsonl(path) == 2
        back = aggregate.load_jsonl(path)
        assert [d["name"] for d in back] == ["a", "c"]
        assert back[0]["attrs"] == {"k": 1} and back[1]["value"] == 2.0
        # a killed writer's torn final line is skipped, not fatal
        with open(path, "a") as f:
            f.write('{"kind": "annotation", "na')
        assert len(aggregate.load_jsonl(path)) == 2
        # ... but a malformed interior line is corruption and raises
        with open(path, "a") as f:
            f.write("\n{}\n")
        with pytest.raises(json.JSONDecodeError):
            aggregate.load_jsonl(path)

    def test_max_events_env_knob(self, monkeypatch):
        monkeypatch.setenv(events.ENV_MAX_EVENTS, "7")
        telemetry.reset()
        assert events.get_log().max_events == 7


# -- registry ------------------------------------------------------------------


class TestRegistry:
    def test_counter_gauge_histogram_snapshot(self):
        reg = registry.get_registry()
        reg.counter("train", "steps").inc(3)
        reg.gauge("serving", "queue_depth").set(5)
        h = reg.histogram("train", "step_s")
        for v in (0.1, 0.2, 0.3, 0.4):
            h.observe(v)
        snap = reg.snapshot()
        assert snap["train"]["steps"] == 3
        assert snap["serving"]["queue_depth"] == 5
        assert snap["train"]["step_s"]["count"] == 4
        assert snap["train"]["step_s"]["p50"] == 0.2
        # same (scope, name) returns the same metric object
        assert reg.counter("train", "steps") is reg.counter("train", "steps")

    def test_counter_rejects_decrease_and_type_conflicts(self):
        reg = registry.get_registry()
        with pytest.raises(ValueError):
            reg.counter("t", "x").inc(-1)
        with pytest.raises(TypeError, match="already registered"):
            reg.gauge("t", "x")

    def test_histogram_ring_keeps_cumulative_count(self):
        h = registry.HistogramMetric("t", "x", max_samples=4)
        for v in range(1, 11):
            h.observe(float(v))
        s = h.summary()
        assert s["count"] == 10 and s["sum"] == 55.0  # cumulative past evict
        assert s["max"] == 10.0  # newest sample survives the ring
        assert h.percentile(0) >= 7.0  # oldest samples (1..6) evicted

    def test_prometheus_text_and_rank_label(self, monkeypatch):
        reg = registry.get_registry()
        reg.counter("serving", "submitted").inc(12)
        h = reg.histogram("train", "step_s")
        h.observe(0.5)
        text = reg.to_prometheus_text()
        assert "# TYPE mlspark_serving_submitted counter" in text
        assert "mlspark_serving_submitted 12" in text
        assert 'mlspark_train_step_s{quantile="0.5"} 0.5' in text
        assert "mlspark_train_step_s_count 1" in text
        monkeypatch.setenv("MLSPARK_PROCESS_ID", "1")
        assert 'mlspark_serving_submitted{rank="1"} 12' in (
            reg.to_prometheus_text()
        )

    def test_name_sanitization(self):
        reg = registry.get_registry()
        reg.counter("serving", "p99.latency-ms").inc()
        assert "mlspark_serving_p99_latency_ms 1" in reg.to_prometheus_text()


# -- flight recorder -----------------------------------------------------------


class TestFlightRecorder:
    def test_dump_and_load(self, tmp_path):
        with telemetry.span("step"):
            telemetry.annotate("checkpoint", step=7)
        path = recorder.dump_flight(
            "test:crash", directory=str(tmp_path), extra={"step": 7}
        )
        assert path == str(tmp_path / "flight_driver.json")
        dump = recorder.load_flight(path)
        assert dump["artifact"] == "flight"
        assert dump["reason"] == "test:crash"
        assert dump["rank"] is None and dump["extra"] == {"step": 7}
        assert dump["event_count"] == len(dump["events"]) == 3
        assert [e["name"] for e in dump["events"]] == [
            "step", "checkpoint", "step",
        ]

    def test_capacity_bounds_the_tail(self, tmp_path):
        for i in range(recorder.FLIGHT_CAPACITY + 50):
            telemetry.annotate(f"a{i}")
        path = recorder.dump_flight("test", directory=str(tmp_path))
        dump = recorder.load_flight(path)
        assert dump["event_count"] == recorder.FLIGHT_CAPACITY
        assert dump["events"][-1]["name"] == f"a{recorder.FLIGHT_CAPACITY + 49}"

    def test_rank_in_file_name(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MLSPARK_PROCESS_ID", "3")
        telemetry.annotate("x")
        path = recorder.dump_flight("test", directory=str(tmp_path))
        assert path.endswith("flight_3.json")
        assert recorder.load_flight(path)["rank"] == 3

    def test_no_directory_means_no_dump(self):
        telemetry.annotate("x")
        assert recorder.dump_flight("test") is None  # never raises


# -- gang aggregation ----------------------------------------------------------


def _write_rank_jsonl(directory, rank, phases):
    """Hand-built rank export: ``phases`` is {name: [durations]}. Events
    carry rank=None on purpose — the merge must stamp rank from the file
    name, which is authoritative."""
    path = os.path.join(directory, aggregate.rank_file_name(rank))
    sid = 0
    t = 0.0
    with open(path, "w") as f:
        for name, durations in phases.items():
            for d in durations:
                sid += 1
                f.write(json.dumps({
                    "kind": "span_start", "name": name, "ts": t,
                    "wall": 1e9 + t, "rank": None, "pid": 1, "span": sid,
                }) + "\n")
                t += d
                f.write(json.dumps({
                    "kind": "span_end", "name": name, "ts": t,
                    "wall": 1e9 + t, "rank": None, "pid": 1, "span": sid,
                    "value": d,
                }) + "\n")
    return path


@pytest.fixture
def two_rank_dir(tmp_path):
    """A synthetic 2-rank gang: rank 1 is a 3x straggler on train.step and
    also the only rank running io.load."""
    d = str(tmp_path / "gang")
    os.makedirs(d)
    _write_rank_jsonl(d, 0, {"train.step": [0.010, 0.010, 0.010, 0.010]})
    _write_rank_jsonl(d, 1, {
        "train.step": [0.030, 0.030, 0.030, 0.030],
        "io.load": [0.5],
    })
    return d


class TestAggregation:
    def test_merge_phase_table_and_skew(self, two_rank_dir):
        report = aggregate.merge_gang_dir(two_rank_dir)
        assert report["ranks"] == [0, 1]
        assert report["event_count"] == 18  # (4 + 4 + 1) spans × 2 events

        step = report["phases"]["train.step"]
        assert step["overall"]["count"] == 8
        assert step["ranks"][0]["p50"] == 0.010
        assert step["ranks"][1]["p99"] == 0.030
        assert report["phases"]["io.load"]["ranks"][1]["count"] == 1

        skew = report["skew"]
        assert "io.load" not in skew  # single-rank phase: no skew entry
        s = skew["train.step"]
        assert s["slowest_rank"] == 1 and s["fastest_rank"] == 0
        assert s["skew_ratio"] == 3.0
        assert abs(s["spread"] - 0.020) < 1e-9

    def test_render_markdown(self, two_rank_dir):
        md = aggregate.render_markdown(aggregate.merge_gang_dir(two_rank_dir))
        assert "# Telemetry report" in md
        assert "| train.step | all | 8 |" in md
        assert "## Rank skew" in md
        assert "| train.step | 1 | 0 | 3.0 |" in md

    def test_write_rank_file_exports_live_log(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MLSPARK_PROCESS_ID", "2")
        with telemetry.span("train.step"):
            pass
        path = aggregate.write_rank_file(str(tmp_path))
        assert path.endswith("telemetry_rank2.jsonl")
        assert aggregate.find_rank_files(str(tmp_path)) == {2: path}
        merged = aggregate.merge_rank_files({2: path})
        assert [e["rank"] for e in merged] == [2, 2]


class TestCommsReport:
    """The comms rollup next to the rank-skew report: zero1 wire-byte
    counters (with bytes/step from the emitter's step stamp) plus the
    comms.* collective span phases."""

    @pytest.fixture
    def comms_dir(self, tmp_path):
        d = str(tmp_path / "gang")
        os.makedirs(d)
        for rank in (0, 1):
            path = _write_rank_jsonl(
                d, rank, {"comms.reduce_scatter": [0.002, 0.002]}
            )
            with open(path, "a") as f:
                f.write(json.dumps({
                    "kind": "counter", "name": "comms.bytes_reduce_scattered",
                    "ts": 1.0, "wall": 1e9, "rank": None, "pid": 1,
                    "value": 4096.0, "attrs": {"steps": 4,
                                               "comms_dtype": "float32"},
                }) + "\n")
        return d

    def test_counters_and_collectives(self, comms_dir):
        report = aggregate.merge_gang_dir(comms_dir)
        comms = report["comms"]
        per_rank = comms["counters"]["comms.bytes_reduce_scattered"]
        assert per_rank[0] == {"total": 4096.0, "steps": 4, "per_step": 1024.0}
        assert per_rank[1]["per_step"] == 1024.0
        coll = comms["collectives"]["comms.reduce_scatter"]
        assert coll["overall"]["count"] == 4
        assert coll["ranks"][0]["p50"] == 0.002
        # Non-comms phases stay out of the collectives table.
        assert "train.step" not in comms["collectives"]

    def test_markdown_section(self, comms_dir):
        md = aggregate.render_markdown(aggregate.merge_gang_dir(comms_dir))
        assert "## Comms" in md
        assert "| comms.bytes_reduce_scattered | 0 | 4096 | 4 | 1024.0 |" in md
        assert "| comms.reduce_scatter | all | 4 |" in md

    def test_section_absent_without_comms_events(self, two_rank_dir):
        report = aggregate.merge_gang_dir(two_rank_dir)
        assert report["comms"] == {
            "counters": {},
            "collectives": {},
            "overlap": {},
            "comms_fraction": None,
            "verdict": None,
        }
        assert "## Comms" not in aggregate.render_markdown(report)


class TestReportCLI:
    """tools/telemetry_report.py against the synthetic 2-rank fixture."""

    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "tools",
                                          "telemetry_report.py"), *argv],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )

    def test_merges_directory_into_json_and_md(self, two_rank_dir, tmp_path):
        json_out = str(tmp_path / "report.json")
        md_out = str(tmp_path / "report.md")
        proc = self._run(two_rank_dir, "--json", json_out, "--md", md_out)
        assert proc.returncode == 0, proc.stderr
        with open(json_out) as f:
            report = json.load(f)
        assert report["artifact"] == "telemetry_report"
        assert report["ranks"] == [0, 1]
        assert report["skew"]["train.step"]["slowest_rank"] == 1
        with open(md_out) as f:
            assert "## Per-phase durations (ms)" in f.read()
        assert "merged 18 events from ranks [0, 1]" in proc.stdout

    def test_markdown_to_stdout_by_default(self, two_rank_dir):
        proc = self._run(two_rank_dir)
        assert proc.returncode == 0, proc.stderr
        assert "# Telemetry report" in proc.stdout

    def test_empty_directory_is_an_error(self, tmp_path):
        proc = self._run(str(tmp_path))
        assert proc.returncode == 1
        assert "no telemetry_rank" in proc.stderr


# -- disabled mode -------------------------------------------------------------


class TestDisabledMode:
    def test_env_kill_switch_spellings(self, monkeypatch):
        for v in ("0", "false", "off", "no", " OFF "):
            monkeypatch.setenv(events.ENV_TELEMETRY, v)
            telemetry.reset()
            assert not events.enabled(), v
        monkeypatch.setenv(events.ENV_TELEMETRY, "1")
        telemetry.reset()
        assert events.enabled()

    def test_noop_singletons_and_nothing_recorded(self, tmp_path):
        events.set_enabled(False)
        # identity, not equality: the no-op path allocates nothing per call
        assert telemetry.span("x") is spans.NOOP_SPAN
        assert telemetry.span("y", a=1) is spans.NOOP_SPAN
        assert events.get_log() is events.NOOP_LOG
        assert registry.get_registry() is registry.NOOP_REGISTRY

        with telemetry.span("x"):
            telemetry.annotate("a")
        registry.get_registry().counter("t", "c").inc()
        assert len(events.get_log()) == 0
        assert registry.get_registry().snapshot() == {}
        assert registry.get_registry().to_prometheus_text() == ""
        assert recorder.dump_flight("test", directory=str(tmp_path)) is None
        assert os.listdir(str(tmp_path)) == []
        assert events.get_log().export_jsonl(str(tmp_path / "x.jsonl")) == 0

    def test_timed_span_still_prints_when_disabled(self):
        events.set_enabled(False)
        lines = []
        with spans.timed_span("Training Time", emit=lines.append):
            pass
        assert len(lines) == 1 and lines[0].startswith("Training Time: ")
        assert len(events.get_log()) == 0


# -- back-compat re-exports ----------------------------------------------------


class TestBackCompat:
    def test_utils_timing_reexports(self):
        from machine_learning_apache_spark_tpu.utils import timing

        assert timing.Timer is spans.Timer
        assert timing.timed_span is spans.timed_span

    def test_timed_span_lands_on_the_timeline(self):
        lines = []
        with spans.timed_span("Epoch Time", emit=lines.append):
            pass
        assert lines and lines[0].startswith("Epoch Time: ")
        names = [e.name for e in events.get_log().snapshot()]
        assert names == ["Epoch Time", "Epoch Time"]  # span_start + span_end

    def test_profiling_annotate_emits_spans(self):
        from machine_learning_apache_spark_tpu.utils.profiling import annotate

        with annotate("square", step=1):
            pass
        evs = events.get_log().snapshot()
        assert [(e.kind, e.name) for e in evs] == [
            ("span_start", "square"), ("span_end", "square"),
        ]
        assert evs[0].attrs == {"step": 1}


# -- the live HTTP plane -------------------------------------------------------


class TestHTTPPlane:
    """telemetry/http.py: endpoint payload functions (no socket), the
    provider registry lifecycle, sidecar discovery, the env port
    contract, and the real server over loopback."""

    def test_metrics_text_includes_registry_and_live_gauges(self):
        registry.get_registry().counter("plane", "hits").inc(3)
        http.register_live_gauge("queue", "depth", lambda: 7.0)
        text = http.metrics_text()
        assert "mlspark_plane_hits 3" in text
        assert "mlspark_queue_depth 7" in text
        # a raising gauge is skipped, never a dead scrape
        http.register_live_gauge("bad", "gauge", lambda: 1 / 0)
        text = http.metrics_text()
        assert "mlspark_queue_depth" in text
        assert "mlspark_bad_gauge" not in text

    def test_healthz_verdict_and_beacon_age(self):
        payload, healthy = http.healthz()
        assert healthy and payload["status"] == "ok"
        assert payload["heartbeat_age_s"] is None  # no beacon yet
        events.beacon_update(phase="train", step=12)
        http.register_health_provider(
            "worker", lambda: {"healthy": True, "note": "fine"}
        )
        payload, healthy = http.healthz()
        assert healthy
        assert payload["phase"] == "train" and payload["step"] == 12
        assert payload["heartbeat_age_s"] is not None
        assert payload["heartbeat_age_s"] < 60.0
        assert payload["checks"]["worker"]["note"] == "fine"
        # one unhealthy check flips the verdict; a raising one does too
        http.register_health_provider("worker", lambda: {"healthy": False})
        payload, healthy = http.healthz()
        assert not healthy and payload["status"] == "degraded"
        http.register_health_provider("worker", lambda: 1 / 0)
        payload, healthy = http.healthz()
        assert not healthy
        assert "error" in payload["checks"]["worker"]

    def test_statusz_sections_and_provider_isolation(self, monkeypatch):
        monkeypatch.setenv("MLSPARK_DP_MODE", "zero1")
        http.register_status_provider("good", lambda: {"answer": 42})
        http.register_status_provider("bad", lambda: 1 / 0)
        payload = http.statusz()
        assert payload["artifact"] == "statusz"
        assert payload["config"]["MLSPARK_DP_MODE"] == "zero1"
        assert payload["sections"]["good"] == {"answer": 42}
        assert "error" in payload["sections"]["bad"]  # isolated, not fatal
        assert "python" in payload["build"]

    def test_flightz_tails_the_ring(self):
        for i in range(20):
            telemetry.annotate("tick", i=i)
        payload = http.flightz(5)
        assert payload["event_count"] == 5
        assert [e["attrs"]["i"] for e in payload["events"]] == list(
            range(15, 20)
        )

    def test_unregister_drops_status_health_and_gauges(self):
        http.register_status_provider("serving", lambda: {})
        http.register_health_provider("serving", lambda: {"healthy": False})
        http.register_live_gauge("serving", "queue_depth", lambda: 1.0)
        http.unregister_provider("serving")
        payload, healthy = http.healthz()
        assert healthy and "serving" not in payload["checks"]
        assert "serving" not in http.statusz()["sections"]
        assert "mlspark_serving_queue_depth" not in http.metrics_text()

    def test_port_sidecar_round_trip(self, tmp_path):
        path = http.write_port_sidecar(1234, directory=str(tmp_path), rank=3)
        assert path and path.endswith("http_rank3.json")
        (tmp_path / "http_rank9.json").write_text("{torn")  # skipped
        found = http.find_port_sidecars(str(tmp_path))
        assert list(found) == [3]
        assert found[3]["port"] == 1234 and found[3]["pid"] == os.getpid()
        # no telemetry dir configured -> no sidecar, no crash
        assert http.write_port_sidecar(1234) is None

    def test_http_port_from_env(self, monkeypatch):
        assert http.http_port_from_env() is None
        for raw, expect in [
            ("0", 0), ("8080", 8080), ("", None), ("  ", None),
            ("nope", None), ("-1", None), ("70000", None),
        ]:
            monkeypatch.setenv(http.ENV_TELEMETRY_HTTP, raw)
            assert http.http_port_from_env() == expect, raw

    def test_server_disabled_means_zero_threads(self, monkeypatch):
        import threading

        # no MLSPARK_TELEMETRY_HTTP: no server, no thread
        before = threading.active_count()
        assert http.start_http_server() is None
        assert threading.active_count() == before
        assert http.get_http_server() is None
        # telemetry killed outright: even an explicit port starts nothing
        monkeypatch.setenv(events.ENV_TELEMETRY, "0")
        telemetry.reset()
        monkeypatch.setenv(http.ENV_TELEMETRY_HTTP, "0")
        assert http.start_http_server() is None
        assert threading.active_count() == before

    def test_server_end_to_end_scrape(self, tmp_path, monkeypatch):
        import urllib.error
        import urllib.request

        monkeypatch.setenv(events.ENV_TELEMETRY_DIR, str(tmp_path))
        telemetry.reset()
        registry.get_registry().counter("scrape", "count").inc(2)
        http.register_health_provider("w", lambda: {"healthy": True})
        srv = http.start_http_server(0, rank=1)
        assert srv is not None and srv.port > 0
        assert http.start_http_server(0) is srv  # idempotent
        # sidecar published + beacon carries the port
        assert http.find_port_sidecars(str(tmp_path))[1]["port"] == srv.port
        assert events.beacon()["http_port"] == srv.port

        def get(path):
            with urllib.request.urlopen(srv.url(path), timeout=10) as r:
                return r.read().decode(), r.status

        body, code = get("/metrics")
        assert code == 200 and "mlspark_scrape_count 2" in body
        body, code = get("/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        body, code = get("/statusz")
        assert code == 200 and json.loads(body)["artifact"] == "statusz"
        body, code = get("/flightz?n=3")
        assert code == 200 and json.loads(body)["event_count"] <= 3
        with pytest.raises(urllib.error.HTTPError) as ei:
            get("/nope")
        assert ei.value.code == 404
        # degraded health answers 503 with the payload attached
        http.register_health_provider("w", lambda: {"healthy": False})
        with pytest.raises(urllib.error.HTTPError) as ei:
            get("/healthz")
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "degraded"
        sidecar = srv.sidecar_path
        http.stop_http_server()
        assert http.get_http_server() is None
        assert not os.path.exists(sidecar)  # sidecar retracted on stop


class TestBeacon:
    def test_update_and_reset(self):
        assert events.beacon() == {}
        events.beacon_update(phase="train", step=3)
        b = events.beacon()
        assert b["phase"] == "train" and b["step"] == 3
        assert "ts" in b and "wall" in b
        events.beacon_update(step=4)  # merge, not replace
        assert events.beacon()["phase"] == "train"
        assert events.beacon()["step"] == 4
        telemetry.reset()
        assert events.beacon() == {}

    def test_beacon_works_when_telemetry_disabled(self, monkeypatch):
        """The beacon is liveness, not telemetry: the heartbeat payload
        must carry phase/step even with MLSPARK_TELEMETRY=0."""
        monkeypatch.setenv(events.ENV_TELEMETRY, "0")
        telemetry.reset()
        events.beacon_update(phase="train", step=1)
        assert events.beacon()["phase"] == "train"


class TestRequestReport:
    def _ev(self, rank, trace_id, total, queue=0.001, prefill="miss"):
        return {
            "kind": "annotation", "name": "serving.request", "rank": rank,
            "attrs": {
                "trace_id": trace_id, "total_s": total,
                "queue_wait_s": queue, "ttft_s": total / 2,
                "service_s": total - queue, "launches": 3,
                "prefill": prefill,
            },
        }

    def test_breakdown_slowest_and_prefill_split(self):
        evs = [
            self._ev(0, "r0-a", 0.5, prefill="miss"),
            self._ev(1, "r1-b", 2.0, prefill="hit"),
            self._ev(0, "r0-c", 1.0, prefill="hit"),
        ]
        evs.append({"kind": "annotation", "name": "other", "attrs": {}})
        rep = aggregate.request_report(evs)
        assert rep["breakdown"]["total_s"]["count"] == 3
        assert rep["breakdown"]["total_s"]["max"] == 2.0
        assert rep["by_prefill"] == {"hit": 2, "miss": 1}
        assert [r["trace_id"] for r in rep["slowest"]] == [
            "r1-b", "r0-c", "r0-a"
        ]
        assert rep["slowest"][0]["rank"] == 1

    def test_empty_without_request_events(self):
        rep = aggregate.request_report([])
        assert rep["breakdown"] == {} and rep["slowest"] == []

    def test_markdown_section_renders(self):
        report = {
            "ranks": [0], "event_count": 1, "phases": {}, "skew": {},
            "requests": aggregate.request_report(
                [self._ev(0, "r0-a", 0.25)]
            ),
        }
        md = aggregate.render_markdown(report)
        assert "## Request latency breakdown (ms)" in md
        assert "r0-a" in md

    def test_live_report_round_trip(self):
        """on_trace -> event log -> request_report: the real producer
        feeds the real consumer."""
        from machine_learning_apache_spark_tpu.serving.metrics import (
            ServingMetrics,
        )
        from machine_learning_apache_spark_tpu.serving.queue import (
            RequestTrace,
        )

        class _Req:
            def __init__(self, i):
                self.trace = RequestTrace(f"t-{i}")
                self.trace.mark("submit", 0.0)
                self.trace.mark("admit", 0.01 * (i + 1))
                self.trace.mark("first_token", 0.05)
                self.trace.mark("complete", 0.1 * (i + 1))

        m = ServingMetrics()
        for i in range(3):
            m.on_trace(_Req(i))
        evs = [e.to_dict() for e in events.get_log().snapshot()]
        rep = aggregate.request_report(evs)
        assert rep["breakdown"]["total_s"]["count"] == 3
        assert rep["slowest"][0]["trace_id"] == "t-2"
        assert len(m.request_exemplars()) == 3


class TestStatusMarkdown:
    def test_render_rows_and_step_skew(self):
        rows = [
            {"rank": 1, "status": "ok", "phase": "train", "step": 12,
             "heartbeat_age_s": 0.5, "queue_depth": 3, "in_flight": 2,
             "tokens_per_sec": 123.4, "occupancy": 0.25, "port": 9100},
            {"rank": 0, "status": "unreachable", "step": 10},
        ]
        md = aggregate.render_status_markdown(rows)
        assert md.startswith("# Gang status")
        lines = md.splitlines()
        r0 = next(ln for ln in lines if ln.startswith("| 0 "))
        r1 = next(ln for ln in lines if ln.startswith("| 1 "))
        assert lines.index(r0) < lines.index(r1)  # sorted by rank
        assert "unreachable" in r0
        assert "123.4" in r1 and "9100" in r1
        assert "step skew (max - min): 2" in md

    def test_missing_fields_render_dashes(self):
        md = aggregate.render_status_markdown([{"rank": 0}])
        assert "| 0 | - | - | - |" in md


# -- distributed trace context -------------------------------------------------


class TestTraceContext:
    def test_mint_shape_and_uniqueness(self):
        hexdigits = set("0123456789abcdef")
        ctxs = [tracectx.mint() for _ in range(8)]
        assert all(c is not None and c.sampled for c in ctxs)
        for c in ctxs:
            assert len(c.trace_id) == 32 and set(c.trace_id) <= hexdigits
            assert len(c.span_id) == 16 and set(c.span_id) <= hexdigits
        assert len({c.trace_id for c in ctxs}) == 8

    def test_use_stamps_events_and_restores(self):
        ctx = tracectx.mint()
        assert tracectx.current() is None
        with tracectx.use(ctx):
            assert tracectx.current() is ctx
            telemetry.annotate("traced")
            # use(None) is a passthrough — the active context survives
            with tracectx.use(None):
                assert tracectx.current() is ctx
                telemetry.annotate("still-traced")
        assert tracectx.current() is None
        telemetry.annotate("untraced")
        traces = [e.trace for e in events.get_log().snapshot()]
        assert traces == [ctx.trace_id, ctx.trace_id, None]

    def test_nested_use_restores_outer(self):
        a, b = tracectx.mint(), tracectx.mint()
        with tracectx.use(a):
            with tracectx.use(b):
                assert tracectx.current() is b
            assert tracectx.current() is a

    def test_child_shares_trace_with_fresh_span(self):
        ctx = tracectx.mint()
        kid = tracectx.child(ctx)
        assert kid.trace_id == ctx.trace_id
        assert kid.span_id != ctx.span_id
        assert kid.flags == ctx.flags
        assert tracectx.child(None) is None

    def test_mint_none_when_disabled_or_unsampled(self, monkeypatch):
        monkeypatch.setenv(tracectx.ENV_TRACE, "0")
        telemetry.reset()
        assert not tracectx.trace_enabled()
        assert tracectx.mint() is None

        monkeypatch.delenv(tracectx.ENV_TRACE, raising=False)
        monkeypatch.setenv(tracectx.ENV_TRACE_SAMPLE, "0.0")
        telemetry.reset()
        assert tracectx.trace_enabled()
        assert tracectx.mint() is None  # head sampler declines
        assert tracectx.mint(sampled=True) is not None  # explicit override

        # tracing never outlives telemetry itself
        monkeypatch.delenv(tracectx.ENV_TRACE_SAMPLE, raising=False)
        telemetry.reset()
        events.set_enabled(False)
        assert not tracectx.trace_enabled()
        assert tracectx.mint() is None

    def test_sample_rate_clamps_and_tolerates_garbage(self, monkeypatch):
        for raw, expect in [("0.25", 0.25), ("2.5", 1.0), ("-1", 0.0),
                            ("nope", 1.0), ("", 1.0)]:
            monkeypatch.setenv(tracectx.ENV_TRACE_SAMPLE, raw)
            telemetry.reset()
            assert tracectx.sample_rate() == expect, raw

    def test_traceparent_round_trip(self):
        ctx = tracectx.mint()
        header = tracectx.to_traceparent(ctx)
        assert header == f"00-{ctx.trace_id}-{ctx.span_id}-01"
        back = tracectx.parse_traceparent(header)
        assert back == ctx
        # uppercase and surrounding whitespace are tolerated on the wire
        assert tracectx.parse_traceparent(f"  {header.upper()}  ") == ctx

    def test_traceparent_garbage_yields_none(self):
        good_trace, good_span = "ab" * 16, "cd" * 8
        bad = [
            None,
            b"00-" + b"ab" * 16,
            "",
            "not-a-header",
            f"00-{good_trace}-{good_span}",          # missing flags
            f"00-{good_trace}-{good_span}-01-extra",  # too many parts
            f"ff-{good_trace}-{good_span}-01",        # forbidden version
            f"0x-{good_trace}-{good_span}-01",        # non-hex version
            f"00-{'0' * 32}-{good_span}-01",          # all-zero trace id
            f"00-{good_trace}-{'0' * 16}-01",         # all-zero span id
            f"00-{good_trace[:-2]}-{good_span}-01",   # short trace id
            f"00-{good_trace}-{good_span}-zz",        # non-hex flags
        ]
        for header in bad:
            assert tracectx.parse_traceparent(header) is None, header


# -- traceview: stitching, completeness, Perfetto export -----------------------


def _fleet_trace_events(tid="ab" * 16, wire="11" * 8, with_attempt=True):
    """Synthetic router (pid 100, driver) + replica (pid 200, rank 1)
    exports for one traced request, joined by the ctx_span/remote_parent
    cross-process edge."""
    router = [
        {"kind": "span_start", "name": "fleet.submit", "ts": 0.0,
         "wall": 100.0, "rank": None, "pid": 100, "span": 1,
         "parent": None, "trace": tid},
        {"kind": "span_end", "name": "fleet.submit", "ts": 0.5,
         "wall": 100.5, "rank": None, "pid": 100, "span": 1,
         "parent": None, "trace": tid, "value": 0.5},
        {"kind": "annotation", "name": "fleet.request", "ts": 0.5,
         "wall": 100.5, "rank": None, "pid": 100, "trace": tid,
         "attrs": {"outcome": "completed"}},
    ]
    if with_attempt:
        router[1:1] = [
            {"kind": "span_start", "name": "fleet.attempt", "ts": 0.01,
             "wall": 100.01, "rank": None, "pid": 100, "span": 2,
             "parent": 1, "trace": tid,
             "attrs": {"replica": 1, "ctx_span": wire}},
            {"kind": "span_end", "name": "fleet.attempt", "ts": 0.4,
             "wall": 100.4, "rank": None, "pid": 100, "span": 2,
             "parent": 1, "trace": tid, "value": 0.39},
        ]
    replica = [
        {"kind": "span_start", "name": "fleet.replica", "ts": 5.0,
         "wall": 100.02, "rank": 1, "pid": 200, "span": 7, "parent": None,
         "trace": tid, "attrs": {"remote_parent": wire}},
        {"kind": "span_end", "name": "fleet.replica", "ts": 5.3,
         "wall": 100.35, "rank": 1, "pid": 200, "span": 7, "parent": None,
         "trace": tid, "value": 0.33},
        {"kind": "counter", "name": "queue.depth", "ts": 5.1,
         "wall": 100.1, "rank": 1, "pid": 200, "value": 3.0},
    ]
    return router + replica


class TestTraceView:
    def test_assemble_resolves_remote_edge(self):
        trees = traceview.assemble(_fleet_trace_events())
        assert list(trees) == ["ab" * 16]
        tree = trees["ab" * 16]
        assert [n["name"] for n in tree["roots"]] == ["fleet.submit"]
        assert tree["orphans"] == []
        assert tree["span_count"] == 3
        attempt = tree["roots"][0]["children"][0]
        assert attempt["name"] == "fleet.attempt"
        rep = attempt["children"][0]
        assert rep["name"] == "fleet.replica"
        assert rep["via"] == "remote"
        assert rep["rank"] == 1 and rep["dur_s"] == 0.33
        assert [a["name"] for a in tree["annotations"]] == ["fleet.request"]
        summary = traceview.trace_summary(tree)
        assert summary["complete"] is True
        assert summary["root"] == "fleet.submit"
        assert summary["total_s"] == 0.5
        assert summary["processes"] == 2

    def test_unresolved_remote_parent_is_an_orphan(self):
        trees = traceview.assemble(
            _fleet_trace_events(with_attempt=False)
        )
        tree = trees["ab" * 16]
        assert [n["name"] for n in tree["orphans"]] == ["fleet.replica"]
        summary = traceview.trace_summary(tree)
        assert summary["complete"] is False
        comp = traceview.completeness(trees)
        assert comp == {"traces": 1, "complete": 0, "fraction": 0.0}

    def test_completeness_and_slowest_over_many_traces(self):
        evs = _fleet_trace_events(tid="aa" * 16, wire="11" * 8)
        slow = [
            {"kind": "span_start", "name": "fleet.submit", "ts": 0.0,
             "wall": 200.0, "rank": None, "pid": 100, "span": 9,
             "parent": None, "trace": "bb" * 16},
            {"kind": "span_end", "name": "fleet.submit", "ts": 2.0,
             "wall": 202.0, "rank": None, "pid": 100, "span": 9,
             "parent": None, "trace": "bb" * 16, "value": 2.0},
        ]
        trees = traceview.assemble(evs + slow)
        comp = traceview.completeness(trees)
        assert comp == {"traces": 2, "complete": 2, "fraction": 1.0}
        rows = traceview.slowest(trees, n=10)
        assert [r["trace_id"] for r in rows] == ["bb" * 16, "aa" * 16]
        assert traceview.slowest(trees, n=1)[0]["total_s"] == 2.0

    def test_perfetto_export_shape(self):
        doc = traceview.perfetto_export(_fleet_trace_events())
        assert doc["displayTimeUnit"] == "ms"
        evs = doc["traceEvents"]
        json.dumps(doc)  # valid Chrome trace JSON end to end
        by_ph = {}
        for e in evs:
            by_ph.setdefault(e["ph"], []).append(e)
        # 3 slices, one s->f flow over the remote edge, 1 instant,
        # 1 counter, and name+sort metadata for both processes
        assert len(by_ph["X"]) == 3
        assert len(by_ph["s"]) == len(by_ph["f"]) == 1
        assert len(by_ph["i"]) == 1
        assert len(by_ph["C"]) == 1
        assert len(by_ph["M"]) == 4
        # replica rows key on gang rank, driver rows on OS pid
        assert {e["pid"] for e in by_ph["X"]} == {100, 1}
        names = {e["args"]["name"] for e in by_ph["M"]
                 if e["name"] == "process_name"}
        assert names == {"driver pid=100", "rank 1"}
        # flow arrow ties the attempt slice to the replica slice
        s, f = by_ph["s"][0], by_ph["f"][0]
        assert s["id"] == f["id"] == "11" * 8
        assert s["pid"] == 100 and f["pid"] == 1
        # wall-clock micros; traced spans share a per-trace track id
        submit = next(e for e in by_ph["X"] if e["name"] == "fleet.submit")
        assert submit["ts"] == 100.0 * 1e6 and submit["dur"] == 0.5 * 1e6
        assert submit["tid"] == int("ab" * 4, 16) & 0x3FFFFFFF

    def test_perfetto_trace_filter_and_untraced_track(self):
        evs = _fleet_trace_events() + [
            {"kind": "span_start", "name": "train.step", "ts": 9.0,
             "wall": 300.0, "rank": 0, "pid": 300, "span": 42,
             "parent": None},
            {"kind": "span_end", "name": "train.step", "ts": 9.1,
             "wall": 300.1, "rank": 0, "pid": 300, "span": 42,
             "parent": None, "value": 0.1},
        ]
        full = traceview.perfetto_export(evs)
        slices = [e for e in full["traceEvents"] if e["ph"] == "X"]
        train = next(e for e in slices if e["name"] == "train.step")
        assert train["tid"] == 0  # untraced spans share track 0
        only = traceview.perfetto_export(evs, trace_id="ab" * 16)
        names = {e["name"] for e in only["traceEvents"] if e["ph"] == "X"}
        assert "train.step" not in names
        assert "fleet.submit" in names

    def test_tracez_payload_summary_and_tree(self):
        evs = _fleet_trace_events()
        summary = traceview.tracez_payload(evs)
        assert summary["artifact"] == "tracez"
        assert summary["completeness"]["traces"] == 1
        assert len(summary["traces"]) == 1
        tree = traceview.tracez_payload(evs, "ab" * 16)
        assert tree["trace_id"] == "ab" * 16
        assert [n["name"] for n in tree["roots"]] == ["fleet.submit"]
        missing = traceview.tracez_payload(evs, "ff" * 16)
        assert missing["error"] == "unknown trace id"

    def test_live_tracez_endpoint_payload(self):
        """The /tracez payload over the live ring: the real span layer
        feeds the real stitcher."""
        ctx = tracectx.mint()
        with tracectx.use(ctx), telemetry.span("fleet.submit"):
            pass
        payload = http.tracez()
        assert payload["artifact"] == "tracez"
        assert payload["completeness"]["complete"] == 1
        tree = http.tracez(ctx.trace_id)
        assert [n["name"] for n in tree["roots"]] == ["fleet.submit"]

    def test_load_dir_merges_rank_files_and_flight_dumps(self, tmp_path):
        d = str(tmp_path)
        _write_rank_jsonl(d, 0, {"fleet.submit": [0.5]})
        # A crashed replica's only export is its flight dump; its events
        # must merge in (rank-stamped) without duplicating rank files.
        with open(os.path.join(d, "flight_1.json"), "w") as f:
            json.dump({"rank": 1, "events": [
                {"kind": "span_start", "name": "fleet.replica", "ts": 0.1,
                 "wall": 1e9, "rank": None, "pid": 2, "span": 1},
            ]}, f)
        evs = traceview.load_dir(d)
        assert len(evs) == 3
        replica = next(e for e in evs if e["name"] == "fleet.replica")
        assert replica["rank"] == 1
        # dedup: re-listing the same events in a second dump adds nothing
        with open(os.path.join(d, "flight_2.json"), "w") as f:
            json.dump({"rank": 1, "events": [dict(replica)]}, f)
        assert len(traceview.load_dir(d)) == 3


# -- aggregate: the mtime/size-keyed JSONL parse cache -------------------------


class TestParseCache:
    def _write(self, path, names):
        with open(path + ".tmp", "w") as f:
            for i, name in enumerate(names):
                f.write(json.dumps({
                    "kind": "annotation", "name": name, "ts": float(i),
                    "wall": 1e9 + i, "rank": None, "pid": 1,
                }) + "\n")
        os.replace(path + ".tmp", path)

    def test_hit_returns_fresh_outer_list(self, tmp_path):
        path = str(tmp_path / "telemetry_rank0.jsonl")
        self._write(path, ["a", "b"])
        first = aggregate.load_jsonl(path)
        second = aggregate.load_jsonl(path)
        assert first == second
        assert first is not second  # callers own their list
        first.append({"name": "poison"})
        assert [e["name"] for e in aggregate.load_jsonl(path)] == ["a", "b"]

    def test_rewrite_invalidates(self, tmp_path):
        path = str(tmp_path / "telemetry_rank0.jsonl")
        self._write(path, ["a"])
        assert len(aggregate.load_jsonl(path)) == 1
        self._write(path, ["a", "b", "c"])  # atomic replace, new stamp
        assert len(aggregate.load_jsonl(path)) == 3

    def test_merge_rank_stamping_does_not_poison_cache(self, tmp_path):
        path = str(tmp_path / aggregate.rank_file_name(3))
        self._write(path, ["a"])
        merged = aggregate.merge_rank_files({3: path})
        assert merged[0]["rank"] == 3  # stamped on a copy
        assert aggregate.load_jsonl(path)[0]["rank"] is None

    def test_reset_clears_the_cache(self, tmp_path):
        path = str(tmp_path / "telemetry_rank0.jsonl")
        self._write(path, ["a"])
        aggregate.load_jsonl(path)
        assert aggregate._PARSE_CACHE
        telemetry.reset()
        assert not aggregate._PARSE_CACHE

    def test_cache_is_bounded(self, tmp_path):
        for i in range(aggregate._PARSE_CACHE_MAX + 8):
            path = str(tmp_path / f"telemetry_rank{i}.jsonl")
            self._write(path, ["a"])
            aggregate.load_jsonl(path)
        assert len(aggregate._PARSE_CACHE) <= aggregate._PARSE_CACHE_MAX
