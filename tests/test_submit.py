"""mlspark-submit: the spark-submit analogue (reference L0 submit mode) —
conf normalization plus an end-to-end empty-builder conf read-back
(``distributed_cnn.py:41-43``)."""

import os
import sys

import pytest

from machine_learning_apache_spark_tpu.submit import _conf_to_env, build_env, main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestConfMapping:
    def test_spark_key_normalizes(self):
        assert _conf_to_env("spark.executor.instances", "4") == (
            "MLSPARK_EXECUTOR_INSTANCES", "4",
        )

    def test_bare_key_normalizes(self):
        assert _conf_to_env("executor_instances", "2") == (
            "MLSPARK_EXECUTOR_INSTANCES", "2",
        )

    def test_bad_conf_rejected(self, tmp_path):
        script = tmp_path / "s.py"
        script.write_text("pass")
        with pytest.raises(SystemExit, match="key=value"):
            main(["--conf", "no-equals-sign", str(script)])

    def test_missing_script_rejected(self):
        with pytest.raises(SystemExit, match="not found"):
            main(["/nonexistent/driver.py"])

    def test_num_processes_feeds_executor_instances(self):
        import argparse

        ns = argparse.Namespace(
            conf=None, name=None, platform=None, coordinator="h:1234",
            num_processes=4, process_id=1,
        )
        env = build_env(ns)
        assert env["MLSPARK_NUM_PROCESSES"] == "4"
        assert env["MLSPARK_EXECUTOR_INSTANCES"] == "4"  # conf read-back
        assert env["MLSPARK_COORDINATOR"] == "h:1234"
        assert env["MLSPARK_PROCESS_ID"] == "1"


class TestSubmitEndToEnd:
    def test_empty_builder_reads_submitted_conf(self, tmp_path, monkeypatch):
        """The reference's submit-mode contract: the driver builds a session
        from an EMPTY conf and reads spark.executor.instances back."""
        out_file = tmp_path / "result.txt"
        driver = tmp_path / "driver.py"
        driver.write_text(
            "import sys\n"
            "from machine_learning_apache_spark_tpu import Session\n"
            "s = Session.builder.getOrCreate()\n"
            "open(sys.argv[1], 'w').write(\n"
            "    f'{s.conf.app_name}:{s.conf.executor_instances}')\n"
            "s.stop()\n"
        )
        monkeypatch.setenv(
            "PYTHONPATH", REPO + os.pathsep + os.environ.get("PYTHONPATH", "")
        )
        rc = main([
            "--conf", "spark.executor.instances=3",
            "--name", "SubmitSmoke",
            "--platform", "cpu",
            str(driver), str(out_file),
        ])
        assert rc == 0
        assert out_file.read_text() == "SubmitSmoke:3"
