"""Pipeline parallelism tests: exact parity with the sequential stage loop
(forward and gradients) on the 8-virtual-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from machine_learning_apache_spark_tpu.parallel import make_mesh
from machine_learning_apache_spark_tpu.parallel.mesh import (
    DATA_AXIS,
    PIPELINE_AXIS,
)
from machine_learning_apache_spark_tpu.parallel.pipeline_parallel import (
    pipeline_apply,
)


def stage_fn(params, x):
    """A residual MLP block — the homogeneous-stack shape."""
    w, b = params["w"], params["b"]
    return x + jnp.tanh(x @ w + b)


def make_stage_params(n_stages, d, seed=0):
    ks = jax.random.split(jax.random.key(seed), 2)
    return {
        "w": 0.3 * jax.random.normal(ks[0], (n_stages, d, d)),
        "b": 0.1 * jax.random.normal(ks[1], (n_stages, d)),
    }


def sequential_reference(stage_params, x):
    n_stages = stage_params["w"].shape[0]
    for s in range(n_stages):
        x = stage_fn(jax.tree.map(lambda p: p[s], stage_params), x)
    return x


class TestPipelineParity:
    @pytest.mark.parametrize("n_stages,n_micro", [(4, 4), (4, 8), (8, 8), (2, 6)])
    def test_forward_matches_sequential(self, n_stages, n_micro):
        mesh = make_mesh({PIPELINE_AXIS: n_stages}, devices=jax.devices()[:n_stages])
        params = make_stage_params(n_stages, d=6)
        x = jax.random.normal(jax.random.key(1), (24, 6))
        out = pipeline_apply(stage_fn, params, x, mesh, n_micro=n_micro)
        np.testing.assert_allclose(
            out, sequential_reference(params, x), atol=1e-5
        )

    def test_gradients_match_sequential(self):
        mesh = make_mesh({PIPELINE_AXIS: 4}, devices=jax.devices()[:4])
        params = make_stage_params(4, d=4)
        x = jax.random.normal(jax.random.key(2), (8, 4))

        g_pipe = jax.grad(
            lambda p: (pipeline_apply(stage_fn, p, x, mesh) ** 2).sum()
        )(params)
        g_seq = jax.grad(
            lambda p: (sequential_reference(p, x) ** 2).sum()
        )(params)
        for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq)):
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_jit(self):
        mesh = make_mesh({PIPELINE_AXIS: 4}, devices=jax.devices()[:4])
        params = make_stage_params(4, d=6)
        x = jax.random.normal(jax.random.key(3), (16, 6))
        out = jax.jit(
            lambda p, x: pipeline_apply(stage_fn, p, x, mesh)
        )(params, x)
        np.testing.assert_allclose(
            out, sequential_reference(params, x), atol=1e-5
        )


class TestPipelineValidation:
    def test_bad_batch_split(self):
        mesh = make_mesh({PIPELINE_AXIS: 4}, devices=jax.devices()[:4])
        params = make_stage_params(4, d=6)
        x = jnp.ones((10, 6))
        with pytest.raises(ValueError, match="not divisible"):
            pipeline_apply(stage_fn, params, x, mesh, n_micro=4)

    def test_bad_stage_count(self):
        mesh = make_mesh({PIPELINE_AXIS: 4}, devices=jax.devices()[:4])
        params = make_stage_params(3, d=6)
        with pytest.raises(ValueError, match="stages"):
            pipeline_apply(stage_fn, params, jnp.ones((8, 6)), mesh)

    def test_extra_mesh_axes_rejected(self):
        from machine_learning_apache_spark_tpu.parallel.mesh import MODEL_AXIS

        mesh = make_mesh({PIPELINE_AXIS: 4, MODEL_AXIS: 2})
        params = make_stage_params(4, d=6)
        with pytest.raises(ValueError, match="extra nontrivial axes"):
            pipeline_apply(stage_fn, params, jnp.ones((8, 6)), mesh)


class TestPipelineWithDataParallel:
    def test_dp_pp_forward_matches_sequential(self):
        """On a dp×pp mesh the microbatch dim shards over "data" while the
        stages ring over "pipeline"; the result is unchanged."""
        mesh = make_mesh({DATA_AXIS: 2, PIPELINE_AXIS: 4})
        params = make_stage_params(4, d=6)
        x = jax.random.normal(jax.random.key(4), (16, 6))
        out = pipeline_apply(stage_fn, params, x, mesh)
        np.testing.assert_allclose(
            out, sequential_reference(params, x), atol=1e-5
        )

    def test_aux_threading(self):
        """Per-microbatch aux constants reach the stage that is processing
        that microbatch (the mask/memory channel of the Transformer ring)."""
        mesh = make_mesh({DATA_AXIS: 2, PIPELINE_AXIS: 4})
        params = make_stage_params(4, d=6)
        x = jax.random.normal(jax.random.key(5), (16, 6))
        scale = jax.random.uniform(jax.random.key(6), (16, 1)) + 0.5

        def aux_stage(p, h, aux_m, rep_m, stage_id, t):
            (s,) = aux_m
            return h + jnp.tanh(h @ p["w"] + p["b"]) * s

        def aux_sequential(params, x, scale):
            h = x
            for s in range(4):
                p = jax.tree.map(lambda q: q[s], params)
                h = h + jnp.tanh(h @ p["w"] + p["b"]) * scale
            return h

        out = pipeline_apply(aux_stage, params, x, mesh, aux=(scale,))
        np.testing.assert_allclose(
            out, aux_sequential(params, x, scale), atol=1e-5
        )


class TestPipelineTransformer:
    """The flagship model over the pipeline schedule — parity with the
    sequential Flax apply (the recipe's pipeline_parallel flag contract)."""

    @pytest.fixture(scope="class")
    def setup(self):
        import flax.linen as nn

        from machine_learning_apache_spark_tpu.models import (
            Transformer,
            TransformerConfig,
        )

        cfg = TransformerConfig(
            src_vocab_size=64, trg_vocab_size=64, d_model=16, ffn_hidden=32,
            num_heads=4, num_layers=4, max_len=16, dropout=0.1,
        )
        model = Transformer(cfg)
        rng = jax.random.key(0)
        src = jax.random.randint(rng, (8, 12), 1, 64, dtype=jnp.int32)
        trg = jax.random.randint(rng, (8, 10), 1, 64, dtype=jnp.int32)
        params = nn.unbox(model.init(rng, src, trg))["params"]
        mesh = make_mesh({DATA_AXIS: 2, PIPELINE_AXIS: 4})
        return model, params, src, trg, mesh

    def test_forward_parity(self, setup):
        from machine_learning_apache_spark_tpu.parallel.pipeline_transformer import (
            pipeline_transformer_logits,
        )

        model, params, src, trg, mesh = setup
        ref = model.apply({"params": params}, src, trg, deterministic=True)
        out = pipeline_transformer_logits(
            model, params, src, trg, mesh, deterministic=True
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_grad_parity(self, setup):
        from machine_learning_apache_spark_tpu.parallel.pipeline_transformer import (
            pipeline_transformer_logits,
        )

        model, params, src, trg, mesh = setup
        g_seq = jax.grad(
            lambda p: (
                model.apply({"params": p}, src, trg, deterministic=True) ** 2
            ).mean()
        )(params)
        g_pp = jax.grad(
            lambda p: (
                pipeline_transformer_logits(
                    model, p, src, trg, mesh, deterministic=True
                ) ** 2
            ).mean()
        )(params)
        for a, b in zip(jax.tree.leaves(g_seq), jax.tree.leaves(g_pp)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-4
            )

    def test_remat_parity(self, setup):
        """cfg.remat is honored inside the pipelined region (layers wrapped
        in jax.checkpoint) with identical forward values and gradients."""
        import dataclasses

        from machine_learning_apache_spark_tpu.models import Transformer
        from machine_learning_apache_spark_tpu.parallel.pipeline_transformer import (
            pipeline_transformer_logits,
        )

        model, params, src, trg, mesh = setup
        remat_model = Transformer(dataclasses.replace(model.cfg, remat=True))
        ref = model.apply({"params": params}, src, trg, deterministic=True)
        out = pipeline_transformer_logits(
            remat_model, params, src, trg, mesh, deterministic=True
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
        g_pp = jax.grad(
            lambda p: (
                pipeline_transformer_logits(
                    remat_model, p, src, trg, mesh, deterministic=True
                ) ** 2
            ).mean()
        )(params)
        g_seq = jax.grad(
            lambda p: (
                model.apply({"params": p}, src, trg, deterministic=True) ** 2
            ).mean()
        )(params)
        for a, b in zip(jax.tree.leaves(g_seq), jax.tree.leaves(g_pp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)

    def test_dropout_path_jits(self, setup):
        from machine_learning_apache_spark_tpu.parallel.pipeline_transformer import (
            pipeline_transformer_logits,
        )

        model, params, src, trg, mesh = setup
        out = jax.jit(
            lambda p, r: pipeline_transformer_logits(
                model, p, src, trg, mesh, rng=r, deterministic=False
            )
        )(params, jax.random.key(1))
        assert bool(jnp.isfinite(out).all())

    def test_validation(self, setup):
        from machine_learning_apache_spark_tpu.parallel.pipeline_transformer import (
            pipeline_transformer_logits,
        )

        model, params, src, trg, _ = setup
        bad_mesh = make_mesh(
            {PIPELINE_AXIS: 3}, devices=jax.devices()[:3]
        )  # 4 layers % 3 stages
        with pytest.raises(ValueError, match="pipeline stages"):
            pipeline_transformer_logits(model, params, src, trg, bad_mesh)
