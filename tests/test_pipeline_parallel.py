"""Pipeline parallelism tests: exact parity with the sequential stage loop
(forward and gradients) on the 8-virtual-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from machine_learning_apache_spark_tpu.parallel import make_mesh
from machine_learning_apache_spark_tpu.parallel.mesh import (
    DATA_AXIS,
    PIPELINE_AXIS,
)
from machine_learning_apache_spark_tpu.parallel.pipeline_parallel import (
    pipeline_apply,
)


def stage_fn(params, x):
    """A residual MLP block — the homogeneous-stack shape."""
    w, b = params["w"], params["b"]
    return x + jnp.tanh(x @ w + b)


def make_stage_params(n_stages, d, seed=0):
    ks = jax.random.split(jax.random.key(seed), 2)
    return {
        "w": 0.3 * jax.random.normal(ks[0], (n_stages, d, d)),
        "b": 0.1 * jax.random.normal(ks[1], (n_stages, d)),
    }


def sequential_reference(stage_params, x):
    n_stages = stage_params["w"].shape[0]
    for s in range(n_stages):
        x = stage_fn(jax.tree.map(lambda p: p[s], stage_params), x)
    return x


class TestPipelineParity:
    @pytest.mark.parametrize("n_stages,n_micro", [(4, 4), (4, 8), (8, 8), (2, 6)])
    def test_forward_matches_sequential(self, n_stages, n_micro):
        mesh = make_mesh({PIPELINE_AXIS: n_stages}, devices=jax.devices()[:n_stages])
        params = make_stage_params(n_stages, d=6)
        x = jax.random.normal(jax.random.key(1), (24, 6))
        out = pipeline_apply(stage_fn, params, x, mesh, n_micro=n_micro)
        np.testing.assert_allclose(
            out, sequential_reference(params, x), atol=1e-5
        )

    def test_gradients_match_sequential(self):
        mesh = make_mesh({PIPELINE_AXIS: 4}, devices=jax.devices()[:4])
        params = make_stage_params(4, d=4)
        x = jax.random.normal(jax.random.key(2), (8, 4))

        g_pipe = jax.grad(
            lambda p: (pipeline_apply(stage_fn, p, x, mesh) ** 2).sum()
        )(params)
        g_seq = jax.grad(
            lambda p: (sequential_reference(p, x) ** 2).sum()
        )(params)
        for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq)):
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_jit(self):
        mesh = make_mesh({PIPELINE_AXIS: 4}, devices=jax.devices()[:4])
        params = make_stage_params(4, d=6)
        x = jax.random.normal(jax.random.key(3), (16, 6))
        out = jax.jit(
            lambda p, x: pipeline_apply(stage_fn, p, x, mesh)
        )(params, x)
        np.testing.assert_allclose(
            out, sequential_reference(params, x), atol=1e-5
        )


class TestPipelineValidation:
    def test_bad_batch_split(self):
        mesh = make_mesh({PIPELINE_AXIS: 4}, devices=jax.devices()[:4])
        params = make_stage_params(4, d=6)
        x = jnp.ones((10, 6))
        with pytest.raises(ValueError, match="not divisible"):
            pipeline_apply(stage_fn, params, x, mesh, n_micro=4)

    def test_bad_stage_count(self):
        mesh = make_mesh({PIPELINE_AXIS: 4}, devices=jax.devices()[:4])
        params = make_stage_params(3, d=6)
        with pytest.raises(ValueError, match="stages"):
            pipeline_apply(stage_fn, params, jnp.ones((8, 6)), mesh)
