"""Text preprocessing tests — golden outputs per SURVEY.md §4 (tokenizer /
vocab / transform chain), mirroring what the reference builds inline at
``pytorch_lstm.py:51-83`` and ``pytorch_machine_translator.py:20-98``."""

import numpy as np
import pytest

from machine_learning_apache_spark_tpu.data.datasets import (
    synthetic_text_classification,
    synthetic_translation_pairs,
)
from machine_learning_apache_spark_tpu.data.text import (
    EOS_ID,
    PAD_ID,
    SOS_ID,
    SPECIALS,
    UNK_ID,
    AddToken,
    PadToLength,
    Sequential,
    TextPipeline,
    ToArray,
    Truncate,
    Vocab,
    VocabTransform,
    basic_english,
    classification_pipeline,
    get_tokenizer,
    translation_pipelines,
    word_punct,
)


class TestTokenizers:
    def test_basic_english_golden(self):
        # torchtext basic_english behavior: lowercase, punct split, quotes gone
        assert basic_english("You can now install TorchText using pip!") == [
            "you", "can", "now", "install", "torchtext", "using", "pip", "!",
        ]

    def test_basic_english_punctuation(self):
        assert basic_english('Hello, "World". Yes?') == [
            "hello", ",", "world", ".", "yes", "?",
        ]

    def test_word_punct(self):
        assert word_punct("Zwei Männer, gehen.") == [
            "zwei", "männer", ",", "gehen", ".",
        ]

    def test_get_tokenizer_resolves(self):
        assert get_tokenizer("basic_english") is basic_english
        custom = lambda s: s.split()
        assert get_tokenizer(custom) is custom
        with pytest.raises(ValueError):
            get_tokenizer("spacy-nonexistent")


class TestVocab:
    def test_specials_first(self):
        v = Vocab.build_from_iterator([["b", "a", "b"]])
        # specials occupy 0..3 in the reference's order (pytorch_lstm.py:58-67)
        assert [v.lookup_token(i) for i in range(4)] == list(SPECIALS)
        assert (PAD_ID, SOS_ID, EOS_ID, UNK_ID) == (0, 1, 2, 3)

    def test_frequency_then_lexical_order(self):
        v = Vocab.build_from_iterator([["b", "a", "b", "c", "a", "b"]])
        # b(3) < a(2) < c(1); ties broken lexically
        assert v.lookup_tokens([4, 5, 6]) == ["b", "a", "c"]

    def test_default_index_is_own_unk(self):
        v = Vocab.build_from_iterator([["x"]])
        assert v["never-seen"] == UNK_ID  # quirk Q11 fixed

    def test_min_freq_and_max_tokens(self):
        v = Vocab.build_from_iterator([["a"] * 3 + ["b"] * 2 + ["c"]], min_freq=2)
        assert "c" not in v and "a" in v and "b" in v
        v2 = Vocab.build_from_iterator([["a"] * 3 + ["b"] * 2 + ["c"]], max_tokens=5)
        assert len(v2) == 5 and "a" in v2 and "b" not in v2

    def test_duplicate_tokens_deduped(self):
        v = Vocab(["hi", "hi", "there"])
        assert len(v) == 6  # 4 specials + 2 unique
        assert v.lookup_tokens(v.lookup_indices(["hi", "there"])) == ["hi", "there"]

    def test_roundtrip(self):
        v = Vocab.build_from_iterator([["hello", "world"]])
        ids = v.lookup_indices(["hello", "world"])
        assert v.lookup_tokens(ids) == ["hello", "world"]


class TestTransforms:
    def test_chain_golden(self):
        """The classification chain (pytorch_lstm.py:70-83): vocab → sos →
        truncate → eos → pad-tensor."""
        v = Vocab(["hi", "there"])
        chain = Sequential(
            VocabTransform(v),
            AddToken(SOS_ID, begin=True),
            Truncate(3),
            AddToken(EOS_ID, begin=False),
            ToArray(PAD_ID),
        )
        out = chain([["hi", "there"], ["hi", "there", "hi", "there"]])
        hi, there = v["hi"], v["there"]
        np.testing.assert_array_equal(
            out,
            [[SOS_ID, hi, there, EOS_ID],
             [SOS_ID, hi, there, EOS_ID]],  # row 2 truncated to 3 incl sos
        )
        assert out.dtype == np.int32

    def test_pad_to_length_fixed_shape(self):
        p = PadToLength(6)
        out = ToArray()(p([[5, 6], [7, 8, 9]]))
        assert out.shape == (2, 6)
        np.testing.assert_array_equal(out[0], [5, 6, 0, 0, 0, 0])

    def test_pad_to_length_clips(self):
        p = PadToLength(2)
        assert p([[1, 2, 3, 4]]) == [[1, 2]]

    def test_to_array_empty(self):
        assert ToArray()([]).shape == (0, 0)


class TestPipelines:
    def test_classification_pipeline_on_synthetic(self):
        texts, labels = synthetic_text_classification(n=64)
        pipe = classification_pipeline(texts, max_seq_len=32)
        ids = pipe(texts)
        assert ids.ndim == 2 and ids.shape[0] == 64 and ids.shape[1] <= 34
        assert (ids[:, 0] == SOS_ID).all()
        # every row terminates with eos then pads
        for row in ids:
            nonpad = row[row != PAD_ID]
            assert nonpad[-1] == EOS_ID

    def test_translation_pipelines_fixed_200(self):
        pairs = synthetic_translation_pairs(n=32)
        src_pipe, trg_pipe = translation_pipelines(pairs, max_len=200)
        src = src_pipe([s for s, _ in pairs])
        trg = trg_pipe([t for _, t in pairs])
        # the reference's hard fixed-length contract (quirk Q8 context):
        # every sentence exactly 200 (pytorch_machine_translator.py:82,97)
        assert src.shape == (32, 200) and trg.shape == (32, 200)

    def test_translation_vocabs_separate(self):
        pairs = synthetic_translation_pairs(n=16)
        src_pipe, trg_pipe = translation_pipelines(pairs, max_len=64)
        # target-language tokens (reversed+zn suffix, never valid source
        # words) are OOV in the source vocab and real ids in their own
        trg_word = pairs[0][1].split()[0]
        assert trg_word not in src_pipe.vocab
        assert src_pipe.vocab[trg_word] == UNK_ID
        assert trg_pipe.vocab[trg_word] != UNK_ID

    def test_fixed_len_too_small_rejected(self):
        v = Vocab(["a"])
        with pytest.raises(ValueError, match="eos would be clipped"):
            TextPipeline(v, max_seq_len=128, fixed_len=128)

    def test_translation_uses_full_capacity(self):
        # a very long sentence fills all max_len slots: sos + content + eos
        long_src = " ".join(["man"] * 300)
        pairs = [(long_src, long_src)]
        src_pipe, _ = translation_pipelines(pairs, max_len=50)
        row = src_pipe([long_src])[0]
        assert row.shape == (50,)
        assert row[0] == SOS_ID and row[-1] == EOS_ID and (row != PAD_ID).all()

    def test_pipeline_fit_unknown_maps_to_unk(self):
        pipe = TextPipeline.fit(["a b c"], max_seq_len=8)
        ids = pipe(["a z"])
        assert UNK_ID in ids[0]

    def test_deterministic(self):
        texts, _ = synthetic_text_classification(n=16)
        pipe = classification_pipeline(texts)
        np.testing.assert_array_equal(pipe(texts), pipe(texts))
