"""ZeRO-1 sharded-update data parallelism (parallel/zero.py).

The contract under test is the one the rewrite is sold on (arxiv
2004.13336): reduce-scatter + shard-local update + allgather is the SAME
optimizer trajectory as replicated data parallelism — bit-identical with
fp32 comms, in BOTH the pipelined (overlap) and serial bucket schedules
— while each chip holds only 1/N of the optimizer state. Hybrid
``data x model`` meshes compose ZeRO-1 with tensor parallelism and must
train to parity with the pure-TP + replicated-DP reference. Plus the
fit() wiring, the env contract, the guard rails, and the telemetry glue
the comms report reads.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from machine_learning_apache_spark_tpu import telemetry
from machine_learning_apache_spark_tpu.models import MLP
from machine_learning_apache_spark_tpu.parallel import (
    DATA_AXIS,
    MODEL_AXIS,
    assert_replicas_in_sync,
    data_model_mesh,
    data_parallel_mesh,
    make_data_parallel_step,
    make_mesh,
    params_fingerprint,
    shard_batch,
    zero,
)
from machine_learning_apache_spark_tpu.parallel.tensor_parallel import (
    shard_state,
)
from machine_learning_apache_spark_tpu.telemetry import registry
from machine_learning_apache_spark_tpu.train import (
    TrainState,
    classification_loss,
    fit,
    make_optimizer,
    make_train_step,
)

pytestmark = pytest.mark.comms

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N = 8  # conftest forces the 8-device CPU mesh


def _setup(rng, n=64, opt="adam", lr=1e-2):
    feats = jnp.asarray(rng.standard_normal((n, 4)), dtype=jnp.float32)
    labels = jnp.asarray(rng.integers(0, 3, n))
    model = MLP(layers=(4, 5, 4, 3))
    params = model.init(jax.random.key(0), feats[:1])["params"]

    def new_state():
        # Fresh buffers per trajectory: the fused steps donate their input.
        return TrainState.create(
            apply_fn=model.apply,
            params=jax.tree.map(jnp.copy, params),
            tx=make_optimizer(opt, lr),
        )

    return model, new_state, (feats, labels)


def _trajectory(step, state, mesh, batch, steps=5):
    sharded = shard_batch(mesh, batch)
    for i in range(steps):
        state, loss, _ = step(state, sharded, jax.random.fold_in(jax.random.key(9), i))
    return jax.device_get(state.params), float(loss)


def _zero1_state(new_state, mesh, **cfg_kw):
    return zero.shard_optimizer_state(
        new_state(), mesh, zero.Zero1Config(**cfg_kw)
    )


class TestZero1Equivalence:
    # The replicated reference trajectory is identical across the dtype/
    # bucket variants (the rng fixture reseeds per test) — computed once;
    # recompiling it per test would roughly double this class's runtime on
    # the single-core CI box.
    _ref_cache: dict = {}

    def _pair(self, rng, mesh, **cfg_kw):
        model, new_state, batch = _setup(rng)
        loss_fn = classification_loss(model.apply)
        if "rep" not in self._ref_cache:
            self._ref_cache["rep"] = _trajectory(
                make_data_parallel_step(loss_fn, mesh), new_state(), mesh,
                batch,
            )[0]
        rep = self._ref_cache["rep"]
        zstate = _zero1_state(new_state, mesh, **cfg_kw)
        z, _ = _trajectory(
            zero.make_zero1_step(loss_fn, mesh, zstate), zstate, mesh, batch
        )
        return rep, z

    def test_fp32_bit_identical(self, rng):
        rep, z = self._pair(rng, data_parallel_mesh())
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b), rep, z
        )

    def test_fp32_bit_identical_multi_bucket(self, rng):
        # 64-byte buckets split the 62-param MLP into several ragged
        # buckets — exercises the per-bucket scatter/gather seams.
        rep, z = self._pair(rng, data_parallel_mesh(), bucket_bytes=64)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b), rep, z
        )

    def test_serial_schedule_bit_identical_multi_bucket(self, rng):
        # overlap=False is the barrier schedule — the pipelined default
        # above must not be the only path that matches the reference.
        rep, z = self._pair(
            rng, data_parallel_mesh(), bucket_bytes=64, overlap=False
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b), rep, z
        )

    def test_overlap_on_off_bit_identical_trajectory(self, rng):
        # Direct pipelined-vs-serial comparison: same 6-step trajectory,
        # 64-byte buckets so every step crosses several bucket seams.
        # The overlap schedule only changes dependency structure, so fp32
        # must match element-for-element, bit-for-bit.
        model, new_state, batch = _setup(rng)
        mesh = data_parallel_mesh()
        loss_fn = classification_loss(model.apply)
        out = {}
        for ov in (True, False):
            zstate = _zero1_state(
                new_state, mesh, bucket_bytes=64, overlap=ov
            )
            out[ov], _ = _trajectory(
                zero.make_zero1_step(loss_fn, mesh, zstate), zstate, mesh,
                batch, steps=6,
            )
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            out[True], out[False],
        )

    def test_bf16_comms_close(self, rng):
        rep, z = self._pair(
            rng, data_parallel_mesh(), comms_dtype="bfloat16"
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-2), rep, z
        )

    def test_int8_comms_trains(self, rng):
        # Per-bucket-scale int8 is lossy; the claim is bounded drift and a
        # finite, sane trajectory — not bit parity.
        rep, z = self._pair(rng, data_parallel_mesh(), comms_dtype="int8")
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=0.2), rep, z
        )
        assert all(np.isfinite(x).all() for x in jax.tree.leaves(z))

    def test_opt_state_is_one_nth_per_chip(self, rng):
        model, new_state, batch = _setup(rng)
        mesh = data_parallel_mesh()
        rep = new_state()
        replicated_bytes = zero.opt_state_bytes(rep.opt_state)
        assert rep.opt_state_bytes == replicated_bytes
        zstate = _zero1_state(new_state, mesh)
        per_chip = zero.opt_state_bytes_per_chip(zstate)
        # ε covers the pad tail and adam's replicated step-count scalar,
        # both O(1) against the moment buffers.
        assert per_chip <= replicated_bytes * (1 / N) + 64
        # And the shards are real shards, not replicas:
        sharded_leaves = [
            leaf for leaf in jax.tree.leaves(zstate.opt_state)
            if hasattr(leaf, "sharding") and not leaf.is_fully_replicated
        ]
        assert sharded_leaves, "no opt-state leaf is actually sharded"


class TestHybridMesh:
    """ZeRO-1 x TP composition on a 2-D ``data x model`` mesh (2x4 on
    the 8-device CPU mesh). The reference is pure TP + replicated DP:
    ``shard_state`` placement + the plain jitted ``make_train_step`` —
    the hybrid step has the same global-batch semantics, so the
    trajectories agree to fp32 reduction-order tolerance."""

    def _hybrid_setup(self, rng):
        feats = jnp.asarray(rng.standard_normal((16, 4)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, 4, 16))
        # Widths divisible by the 4-way model axis so the TP annotations
        # actually shard (MLP alternates ("embed","mlp")/("mlp","embed")).
        model = MLP(layers=(4, 8, 8, 4), tp_rules=True)
        params = model.init(jax.random.key(0), feats[:1])["params"]  # boxed
        return model, params, (feats, labels)

    def _run(self, step, state, mesh, batch, steps=5):
        sharded = shard_batch(mesh, batch)
        for i in range(steps):
            state, loss, _ = step(
                state, sharded, jax.random.fold_in(jax.random.key(9), i)
            )
        return state, float(loss)

    def test_hybrid_matches_tp_reference(self, rng):
        model, params, batch = self._hybrid_setup(rng)
        mesh = data_model_mesh(4)
        assert dict(mesh.shape) == {DATA_AXIS: 2, MODEL_AXIS: 4}
        loss_fn = classification_loss(model.apply)

        ref = shard_state(
            TrainState.create(
                apply_fn=model.apply,
                params=jax.tree.map(jnp.copy, params),
                tx=make_optimizer("adam", 1e-2),
            ),
            mesh,
        )
        ref, ref_loss = self._run(make_train_step(loss_fn), ref, mesh, batch)
        replicated_bytes = zero.opt_state_bytes(ref.opt_state)

        zstate = zero.init_sharded(
            apply_fn=model.apply,
            params=jax.tree.map(jnp.copy, params),
            tx=make_optimizer("adam", 1e-2),
            mesh=mesh,
            config=zero.Zero1Config(bucket_bytes=64),  # multi-bucket
        )
        zstep = zero.make_zero1_step(loss_fn, mesh, zstate)
        zstate, z_loss = self._run(zstep, zstate, mesh, batch)

        assert z_loss == pytest.approx(ref_loss, abs=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            ),
            jax.device_get(ref.params), jax.device_get(zstate.params),
        )
        # Satellite acceptance: per-chip optimizer bytes <= 1/N + ε of
        # the replicated footprint — N is the FULL device count (the
        # flat moments shard jointly over data x model).
        per_chip = zero.opt_state_bytes_per_chip(zstate)
        assert per_chip <= replicated_bytes * (1 / N) + 64
        # TP placement survives the flatten/update/unflatten round trip.
        kernel_specs = [
            str(getattr(leaf.sharding, "spec", ""))
            for leaf in jax.tree.leaves(zstate.params)
        ]
        assert any(MODEL_AXIS in s for s in kernel_specs)
        # And the step carries the byte accounting fit's counters read.
        assert zstep.comms_stats["reduce_scatter_bytes"] > 0

    def test_hybrid_via_fit(self, rng):
        model, params, (feats, labels) = self._hybrid_setup(rng)
        batches = [(feats[i : i + 8], labels[i : i + 8]) for i in (0, 8)]
        state = TrainState.create(
            apply_fn=model.apply,
            params=jax.tree.map(jnp.copy, params),
            tx=make_optimizer("adam", 1e-2),
        )
        res = fit(
            state, classification_loss(model.apply), batches,
            mesh=data_model_mesh(4), dp_mode="zero1", dp_bucket_bytes=256,
            epochs=2, log_every=0, rng=jax.random.key(3),
            emit=lambda s: None,
        )
        assert isinstance(res.state, zero.Zero1State)
        assert np.isfinite(res.final_loss)

    # The pure-TP + replicated-DP reference trajectory is identical
    # across the wire-dtype variants (the rng fixture reseeds per test)
    # — computed once, same cache discipline as TestZero1Equivalence.
    _ref_cache: dict = {}

    def _compressed_wire_pair(self, rng, comms_dtype):
        model, params, batch = self._hybrid_setup(rng)
        mesh = data_model_mesh(4)
        loss_fn = classification_loss(model.apply)
        if "ref" not in self._ref_cache:
            ref = shard_state(
                TrainState.create(
                    apply_fn=model.apply,
                    params=jax.tree.map(jnp.copy, params),
                    tx=make_optimizer("adam", 1e-2),
                ),
                mesh,
            )
            ref, _ = self._run(make_train_step(loss_fn), ref, mesh, batch)
            self._ref_cache["ref"] = jax.device_get(ref.params)
        zstate = zero.init_sharded(
            apply_fn=model.apply,
            params=jax.tree.map(jnp.copy, params),
            tx=make_optimizer("adam", 1e-2),
            mesh=mesh,
            config=zero.Zero1Config(
                bucket_bytes=64, comms_dtype=comms_dtype
            ),
        )
        zstep = zero.make_zero1_step(loss_fn, mesh, zstate)
        zstate, _ = self._run(zstep, zstate, mesh, batch)
        return self._ref_cache["ref"], zstate, zstep

    def test_hybrid_bf16_wire_close(self, rng):
        """bf16 wire on the hybrid mesh: per-bucket QDQ rounding only,
        so the trajectory stays within bf16-mantissa tolerance of the
        pure-TP reference — same documented bound as the explicit path's
        bf16 gate (docs/PARALLELISM.md wire-dtype matrix)."""
        ref, zstate, zstep = self._compressed_wire_pair(rng, "bfloat16")
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-2
            ),
            ref, jax.device_get(zstate.params),
        )
        # The byte counters must show the 2x reduce-scatter shrink.
        fp32 = zero.comms_bytes_per_step(
            zstate.plan, zero.Zero1Config(bucket_bytes=64)
        )
        assert zstep.comms_stats["reduce_scatter_bytes"] == (
            fp32["reduce_scatter_bytes"] // 2
        )
        assert zstep.comms_stats["allgather_bytes"] == (
            fp32["allgather_bytes"]  # params gather fp32 in every mode
        )

    def test_hybrid_int8_wire_trains(self, rng):
        """int8 wire is lossy (per-bucket absmax scale): bounded drift
        and a finite trajectory — not bit parity — mirroring the
        explicit path's int8 gate. The int8 rejection guard this
        replaces is gone: hybrid + compressed wire now composes."""
        ref, zstate, zstep = self._compressed_wire_pair(rng, "int8")
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=0.2
            ),
            ref, jax.device_get(zstate.params),
        )
        assert all(
            np.isfinite(np.asarray(x)).all()
            for x in jax.tree.leaves(jax.device_get(zstate.params))
        )
        # TP placement survives the QDQ'd flatten/update/unflatten.
        specs = [
            str(getattr(leaf.sharding, "spec", ""))
            for leaf in jax.tree.leaves(zstate.params)
        ]
        assert any(MODEL_AXIS in s for s in specs)
        # int8 wire: ~4x shrink plus one fp32 scale per bucket.
        fp32 = zero.comms_bytes_per_step(
            zstate.plan, zero.Zero1Config(bucket_bytes=64)
        )
        n_buckets = len(zstate.plan.buckets)
        assert zstep.comms_stats["reduce_scatter_bytes"] == (
            fp32["reduce_scatter_bytes"] // 4 + 4 * n_buckets
        )


class TestFitWiring:
    def _batches(self, feats, labels):
        return [
            (feats[i : i + 16], labels[i : i + 16]) for i in range(0, 64, 16)
        ]

    def test_fit_zero1_matches_replicated_fit(self, rng):
        model, new_state, (feats, labels) = _setup(rng)
        loss_fn = classification_loss(model.apply)
        batches = self._batches(feats, labels)
        kw = dict(epochs=2, log_every=0, rng=jax.random.key(3), emit=lambda s: None)
        res_rep = fit(
            new_state(), loss_fn, batches, mesh=data_parallel_mesh(), **kw
        )
        res_z = fit(
            new_state(), loss_fn, batches, mesh=data_parallel_mesh(),
            dp_mode="zero1", **kw
        )
        assert isinstance(res_z.state, zero.Zero1State)
        assert params_fingerprint(res_z.state.params) == params_fingerprint(
            res_rep.state.params
        )

    def test_env_contract_resolves_mode_and_knobs(self, rng, monkeypatch):
        monkeypatch.setenv(zero.ENV_DP_MODE, "zero1")
        monkeypatch.setenv(zero.ENV_BUCKET_BYTES, "128")
        monkeypatch.setenv(zero.ENV_COMMS_DTYPE, "bfloat16")
        monkeypatch.setenv(zero.ENV_OVERLAP, "off")
        assert zero.resolve_dp_mode(None) == "zero1"
        cfg = zero.Zero1Config.from_env()
        assert cfg.bucket_bytes == 128 and cfg.comms_dtype == "bfloat16"
        assert cfg.overlap is False
        # Explicit argument beats env:
        assert zero.resolve_dp_mode("replicated") == "replicated"
        assert zero.Zero1Config.from_env(bucket_bytes=256).bucket_bytes == 256
        assert zero.Zero1Config.from_env(overlap=True).overlap is True
        # Unset env -> pipelined default; junk value -> loud error.
        monkeypatch.delenv(zero.ENV_OVERLAP)
        assert zero.Zero1Config.from_env().overlap is True
        monkeypatch.setenv(zero.ENV_OVERLAP, "maybe")
        with pytest.raises(ValueError, match=zero.ENV_OVERLAP):
            zero.Zero1Config.from_env()
        # (fit picking the mode up from env alone is exercised — together
        # with the telemetry counters — in TestTelemetryGlue, sharing one
        # compiled fit instead of paying for two.)

    def test_fit_rejects_bad_combinations(self, rng):
        model, new_state, (feats, labels) = _setup(rng)
        loss_fn = classification_loss(model.apply)
        batches = self._batches(feats, labels)
        kw = dict(epochs=1, log_every=0, emit=lambda s: None)
        with pytest.raises(ValueError, match="mesh"):
            fit(new_state(), loss_fn, batches, dp_mode="zero1", **kw)
        with pytest.raises(ValueError, match="not both"):
            fit(
                new_state(), loss_fn, batches, mesh=data_parallel_mesh(),
                dp_mode="zero1", zero1=True, **kw
            )
        with pytest.raises(ValueError, match="steps_per_call"):
            fit(
                new_state(), loss_fn, batches, mesh=data_parallel_mesh(),
                dp_mode="zero1", steps_per_call=2, **kw
            )
        with pytest.raises(ValueError, match="zero1"):
            fit(
                new_state(), loss_fn, batches, mesh=data_parallel_mesh(),
                dp_comms_dtype="bfloat16", **kw
            )
        with pytest.raises(ValueError, match="zero1"):
            fit(
                new_state(), loss_fn, batches, mesh=data_parallel_mesh(),
                dp_overlap=False, **kw
            )


class TestGuards:
    def test_midrun_shard_raises(self, rng):
        model, new_state, _ = _setup(rng)
        state = new_state().replace(step=3)
        with pytest.raises(ValueError, match="step"):
            zero.shard_optimizer_state(state, data_parallel_mesh())

    def test_pipeline_mesh_raises(self, rng):
        # Hybrid data x model now composes (TestHybridMesh); a pipeline
        # axis restructures the step itself and must still refuse, with
        # an error that names the supported composition.
        model, new_state, _ = _setup(rng)
        mesh = make_mesh({DATA_AXIS: 4, "pipeline": 2})
        with pytest.raises(
            ValueError, match="composes only with tensor parallelism"
        ):
            zero.shard_optimizer_state(new_state(), mesh)

    def test_step_requires_zero1_state(self, rng):
        model, new_state, _ = _setup(rng)
        mesh = data_parallel_mesh()
        loss_fn = classification_loss(model.apply)
        with pytest.raises(TypeError, match="Zero1State"):
            zero.make_zero1_step(loss_fn, mesh, new_state())

    def test_bad_mode_and_dtype_rejected(self, monkeypatch):
        with pytest.raises(ValueError, match="dp_mode"):
            zero.resolve_dp_mode("zero3")
        monkeypatch.setenv(zero.ENV_DP_MODE, "nope")
        with pytest.raises(ValueError, match="dp_mode"):
            zero.resolve_dp_mode(None)
        with pytest.raises(ValueError, match="comms_dtype"):
            zero.Zero1Config(comms_dtype="fp8")
        with pytest.raises(ValueError, match="bucket_bytes"):
            zero.Zero1Config(bucket_bytes=0)

    def test_fingerprint_works_sharded_sync_check_refuses(self, rng):
        # Satellite: params_fingerprint must survive a zero1 state (its
        # params ARE replicated), while assert_replicas_in_sync must
        # refuse a sharded tree loudly instead of allgathering a wrong
        # answer.
        model, new_state, _ = _setup(rng)
        mesh = data_parallel_mesh()
        zstate = _zero1_state(new_state, mesh)
        fp = params_fingerprint(zstate)
        assert np.isfinite(fp)
        assert fp == params_fingerprint(new_state().params)
        assert_replicas_in_sync(zstate)  # params-only view: fine
        with pytest.raises(ValueError, match="replicat"):
            assert_replicas_in_sync(zstate.opt_state)


class TestTelemetryGlue:
    def test_fit_emits_comms_counters(self, rng, monkeypatch):
        # Mode comes from env alone (not the dp_mode argument): this fit
        # doubles as the env-resolution end-to-end check.
        monkeypatch.setenv("MLSPARK_TELEMETRY", "1")
        monkeypatch.setenv(zero.ENV_DP_MODE, "zero1")
        monkeypatch.setenv(zero.ENV_BUCKET_BYTES, "65536")
        telemetry.reset()
        try:
            model, new_state, (feats, labels) = _setup(rng)
            batches = [
                (feats[i : i + 16], labels[i : i + 16])
                for i in range(0, 64, 16)
            ]
            res = fit(
                new_state(), classification_loss(model.apply), batches,
                epochs=2, log_every=0, rng=jax.random.key(3),
                mesh=data_parallel_mesh(),
                emit=lambda s: None,
            )
            assert isinstance(res.state, zero.Zero1State)
            assert res.state.config.bucket_bytes == 65536
            comms = registry.get_registry().snapshot().get("comms", {})
            assert comms["bytes_reduce_scattered"] > 0
            assert comms["bytes_allgathered"] > 0
            assert comms["opt_state_bytes_per_chip"] > 0
            evs = [
                ev.to_dict() for ev in telemetry.get_log().snapshot()
                if ev.kind == "counter"
                and str(ev.name).startswith("comms.")
            ]
            assert {e["name"] for e in evs} == {
                "comms.bytes_reduce_scattered", "comms.bytes_allgathered",
                "comms.bytes_exposed", "comms.bytes_overlapped",
            }
            # 2 epochs × 4 batches, stamped so the report can do bytes/step.
            assert all(e["attrs"]["steps"] == 8 for e in evs)
            assert all(e["attrs"]["overlap"] is True for e in evs)
            by_name = {e["name"]: e["value"] for e in evs}
            # The overlapped/exposed split partitions the wire bytes.
            assert by_name["comms.bytes_exposed"] + by_name[
                "comms.bytes_overlapped"
            ] == by_name["comms.bytes_reduce_scattered"] + by_name[
                "comms.bytes_allgathered"
            ]
        finally:
            telemetry.reset()


def test_comms_bench_smoke_subprocess(tmp_path):
    """tools/comms_bench.py --smoke is the tier-1 CI entry: a fresh
    process, a small sweep covering overlap on/off plus the hybrid leg,
    and the full equivalence gate (replicated parity AND overlap
    bit-identity)."""
    out = tmp_path / "comms_bench.json"
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "tools", "comms_bench.py"),
            "--smoke", "--out", str(out),
        ],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr[-2000:]
    art = json.loads(out.read_text())
    assert art["ok"] is True
    assert art["equivalence"]["bit_identical_float32"] is True
    assert art["equivalence"]["bit_identical_overlap_fp32"] is True
    assert art["equivalence"]["opt_state_ok"] is True
    assert [p["mode"] for p in art["sweep"]] == [
        "replicated", "zero1", "zero1",
    ]
    zero1_points = [p for p in art["sweep"] if p["mode"] == "zero1"]
    assert {p["overlap"] for p in zero1_points} == {True, False}
    # The column the overlap win is read off: pipelining leaves only
    # 1/n_buckets of the standalone collective time exposed.
    on = next(p for p in zero1_points if p["overlap"])
    off = next(p for p in zero1_points if not p["overlap"])
    assert on["n_buckets"] > 1
    assert on["exposed_collective_ms_est"] < off["exposed_collective_ms_est"]
    assert off["hidden_fraction"] == 0.0
    # Hybrid leg: parity with the pure-TP reference + sharded moments,
    # and the compressed-wire column — smoke runs fp32 + bf16; the bf16
    # wire halves the reduce-scatter bytes while the allgather stays
    # fp32, both trajectories inside their parity tolerances.
    assert art["hybrid"]["ok"] is True
    assert art["hybrid"]["parity_ok"] is True
    assert art["hybrid"]["tp_sharding_preserved"] is True
    hw = art["hybrid"]["wire"]
    assert set(hw) == {"float32", "bfloat16"}
    assert hw["bfloat16"]["parity_ok"] is True
    assert hw["bfloat16"]["tp_sharding_preserved"] is True
    assert (
        hw["bfloat16"]["reduce_scatter_bytes"]
        == hw["float32"]["reduce_scatter_bytes"] // 2
    )
    assert (
        hw["bfloat16"]["allgather_bytes"]
        == hw["float32"]["allgather_bytes"]
    )
    assert hw["bfloat16"]["rs_shrink_vs_fp32"] == 2.0
    assert art["comms"]["collectives"].keys() >= {
        "comms.reduce_scatter", "comms.allgather",
    }
