"""Data-layer tests: libsvm round-trip, ArrayFrame, reader API."""

import numpy as np
import pytest

import machine_learning_apache_spark_tpu as mlspark
from machine_learning_apache_spark_tpu.data import (
    ArrayFrame,
    read_libsvm,
    write_libsvm,
)


@pytest.fixture
def libsvm_file(tmp_path, rng):
    """A file shaped like $SPARK_HOME's sample_multiclass_classification_data:
    4 features, 3 classes (mllib_multilayer_perceptron_classifier.py:32)."""
    n = 150
    features = rng.normal(size=(n, 4)).astype(np.float32).round(4)
    features[rng.random(size=features.shape) < 0.3] = 0.0  # sparsity
    labels = rng.integers(0, 3, size=n)
    path = tmp_path / "sample.txt"
    write_libsvm(str(path), features, labels)
    return str(path), features, labels


class TestLibsvm:
    def test_round_trip(self, libsvm_file):
        path, features, labels = libsvm_file
        frame = read_libsvm(path, num_features=4)
        np.testing.assert_allclose(frame.features, features, rtol=1e-5)
        np.testing.assert_array_equal(frame.labels, labels)

    def test_one_based_indices(self, tmp_path):
        p = tmp_path / "f.txt"
        p.write_text("1 1:0.5 3:0.25\n0 2:1.0\n")
        frame = read_libsvm(str(p))
        np.testing.assert_allclose(
            frame.features, [[0.5, 0.0, 0.25], [0.0, 1.0, 0.0]]
        )
        np.testing.assert_array_equal(frame.labels, [1, 0])

    def test_malformed_line_raises(self, tmp_path):
        p = tmp_path / "bad.txt"
        p.write_text("1 0:0.5\n")  # 0-based index is invalid
        with pytest.raises(ValueError, match="malformed libsvm line 1"):
            read_libsvm(str(p))

    def test_num_features_pad_and_overflow(self, tmp_path):
        p = tmp_path / "f.txt"
        p.write_text("0 1:1.0\n")
        assert read_libsvm(str(p), num_features=6).features.shape == (1, 6)
        with pytest.raises(ValueError):
            read_libsvm(str(p), num_features=0)

    def test_comments_and_blank_lines(self, tmp_path):
        p = tmp_path / "f.txt"
        p.write_text("# header\n\n2 1:3.0  # trailing\n")
        frame = read_libsvm(str(p))
        assert len(frame) == 1 and frame.labels[0] == 2


class TestArrayFrame:
    def test_random_split_matches_spark_semantics(self, libsvm_file):
        """60/40 randomSplit(seed=1234) — mllib_…py:27."""
        path, *_ = libsvm_file
        frame = read_libsvm(path)
        train, test = frame.random_split([0.6, 0.4], seed=1234)
        assert len(train) + len(test) == len(frame)
        assert abs(len(train) - 0.6 * len(frame)) <= 1
        # deterministic given seed
        train2, _ = frame.randomSplit([0.6, 0.4], seed=1234)
        np.testing.assert_array_equal(train.features, train2.features)
        # disjoint
        seen = {tuple(r) for r in train.features} & {
            tuple(r) for r in test.features
        }
        assert len(seen) == 0 or len(seen) < len(frame) * 0.05

    def test_arrays_dtypes(self):
        f = ArrayFrame(np.ones((3, 2)), np.array([0.0, 1.0, 2.0]))
        x, y = f.arrays()
        assert x.dtype == np.float32 and y.dtype == np.int64
        assert f.num_features == 2 and f.num_classes == 3

    def test_length_mismatch_raises(self):
        with pytest.raises(ValueError):
            ArrayFrame(np.ones((3, 2)), np.ones(2))


class TestReaderAPI:
    def test_session_read_libsvm(self, libsvm_file):
        path, features, _ = libsvm_file
        session = mlspark.Session.builder.get_or_create()
        frame = session.read.format("libsvm").option("numFeatures", 4).load(path)
        assert frame.features.shape == features.shape
        session.stop()

    def test_csv(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("1.0,2.0,0\n3.0,4.0,1\n")
        frame = mlspark.Session.builder.get_or_create().read.format("csv").load(str(p))
        assert frame.num_features == 2
        np.testing.assert_array_equal(frame.labels, [0, 1])

    def test_image_format(self, tmp_path):
        """``read.format("image")`` loads the FashionMNIST idx layout."""
        import gzip
        import struct

        from machine_learning_apache_spark_tpu.data.reader import DataReader

        raw = tmp_path / "FashionMNIST" / "raw"
        raw.mkdir(parents=True)
        images = np.arange(2 * 28 * 28, dtype=np.uint8).reshape(2, 28, 28)
        labels = np.array([3, 7], dtype=np.uint8)
        with gzip.open(raw / "train-images-idx3-ubyte.gz", "wb") as f:
            f.write(struct.pack(">I", 0x00000803) + struct.pack(">III", 2, 28, 28))
            f.write(images.tobytes())
        with gzip.open(raw / "train-labels-idx1-ubyte.gz", "wb") as f:
            f.write(struct.pack(">I", 0x00000801) + struct.pack(">I", 2))
            f.write(labels.tobytes())
        frame = DataReader().format("image").load(str(tmp_path))
        assert frame.features.shape == (2, 28, 28, 1)
        np.testing.assert_array_equal(frame.labels, [3, 7])

    def test_unknown_format(self):
        from machine_learning_apache_spark_tpu.data.reader import DataReader

        with pytest.raises(ValueError, match="unsupported format"):
            DataReader().format("parquet").load("x")
