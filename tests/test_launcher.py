"""Launcher tests (SURVEY.md §4): spawn N local processes, verify rendezvous
env plumb-through, rank/world assignment, rank-0 result return, and gang
failure propagation."""

import pytest

from machine_learning_apache_spark_tpu.launcher import Distributor, fn_reference
from machine_learning_apache_spark_tpu.launcher.coordinator import RendezvousSpec


class TestFnReference:
    def test_module_function(self):
        from launcher_workers import echo_rank

        assert fn_reference(echo_rank) == "launcher_workers:echo_rank"

    def test_lambda_rejected(self):
        with pytest.raises(ValueError):
            fn_reference(lambda: None)

    def test_string_passthrough(self):
        assert fn_reference("a.b:c") == "a.b:c"
        with pytest.raises(ValueError):
            fn_reference("no_colon")


class TestRendezvousSpec:
    def test_torch_style_env(self, monkeypatch):
        monkeypatch.setenv("MASTER_ADDR", "10.0.0.1")
        monkeypatch.setenv("MASTER_PORT", "1234")
        monkeypatch.setenv("WORLD_SIZE", "4")
        monkeypatch.setenv("RANK", "2")
        spec = RendezvousSpec.from_env()
        assert spec.coordinator_address == "10.0.0.1:1234"
        assert spec.num_processes == 4 and spec.process_id == 2

    def test_single_process_is_none(self, monkeypatch):
        for var in ("MASTER_ADDR", "MLSPARK_COORDINATOR", "WORLD_SIZE"):
            monkeypatch.delenv(var, raising=False)
        assert RendezvousSpec.from_env() is None

    def test_apply_env_roundtrip(self):
        spec = RendezvousSpec("h:29500", 8, 3)
        env = spec.apply_env({})
        assert env["MASTER_ADDR"] == "h" and env["RANK"] == "3"
        assert env["MLSPARK_NUM_PROCESSES"] == "8"


class TestDistributorLocal:
    def test_single_process_inline(self):
        from launcher_workers import echo_rank

        out = Distributor(num_processes=1).run(echo_rank, tag="inline")
        assert out["tag"] == "inline"

    def test_gang_rank0_result(self):
        # 2-process gang: rank 0's dict comes back with correct rank/world env.
        out = Distributor(num_processes=2, platform="cpu", timeout=120).run(
            "launcher_workers:echo_rank", tag="gang"
        )
        assert out == {"rank": 0, "world": 2, "master": "127.0.0.1", "tag": "gang"}

    def test_gang_dp_mode_env_plumbing(self):
        # Distributor(dp_mode="zero1") sets MLSPARK_DP_MODE for every rank
        # — the env contract fit() resolves via parallel.zero.
        out = Distributor(
            num_processes=2, platform="cpu", timeout=120, dp_mode="zero1"
        ).run("launcher_workers:echo_dp_mode")
        assert out == {"dp_mode": "zero1", "rank": 0}

    def test_dp_mode_typo_rejected_at_construction(self):
        with pytest.raises(ValueError, match="dp_mode"):
            Distributor(num_processes=2, dp_mode="zero2")

    def test_gang_failure_raises(self):
        with pytest.raises(RuntimeError, match="worker exploded"):
            Distributor(num_processes=2, platform="cpu", timeout=120).run(
                "launcher_workers:boom"
            )

    def test_gang_restart_recovers(self, tmp_path):
        """max_restarts re-runs the whole gang (Spark-barrier all-or-nothing
        recovery, SURVEY.md §5): first attempt fails, second succeeds."""
        out = Distributor(
            num_processes=2, platform="cpu", timeout=240, max_restarts=1
        ).run("launcher_workers:flaky_until", str(tmp_path / "marker"))
        assert out == {"attempt": "recovered"}

    def test_gang_restart_exhausted_raises(self):
        with pytest.raises(RuntimeError, match="worker exploded"):
            Distributor(
                num_processes=2, platform="cpu", timeout=240, max_restarts=1
            ).run("launcher_workers:boom")

    def test_unpicklable_result_reports_rank_failure(self):
        # A worker whose return value can't be pickled must surface as a gang
        # failure naming the rank — not escape as a raw EOFError/unpickling
        # artifact from a truncated result file.
        with pytest.raises(RuntimeError, match="gang failed"):
            Distributor(num_processes=2, platform="cpu", timeout=120).run(
                "launcher_workers:unpicklable_result"
            )

    def test_single_process_with_platform_spawns(self):
        # n=1 + platform override must not run inline (this interpreter's
        # backend is already initialized) — it spawns and applies the env.
        out = Distributor(num_processes=1, platform="cpu", timeout=120).run(
            "launcher_workers:echo_rank", tag="spawned"
        )
        assert out["tag"] == "spawned" and out["rank"] == 0

    @pytest.mark.slow
    def test_gang_jax_distributed_collective(self):
        # Full rendezvous: 2 CPU processes jax.distributed.initialize and
        # allgather — the gloo-collective parity check (SURVEY.md §2.4).
        out = Distributor(num_processes=2, platform="cpu", timeout=240).run(
            "launcher_workers:cross_process_sum"
        )
        assert out == {"rank": 0, "world": 2, "sum": 3.0}

    @pytest.mark.slow
    def test_gang_dp_train_step_parity(self):
        """A REAL cross-process psum train step (VERDICT round-2 item 6): a
        2-process gang builds a 2-device mesh, each rank feeds its shard,
        grads sync through the compiled collective, replicas stay bit-level
        in sync, and the loss trajectory + final params equal the
        single-process full-batch run
        (``distributed_multilayer_perceptron.py:177-181`` parity)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        out = Distributor(num_processes=2, platform="cpu", timeout=240).run(
            "launcher_workers:dp_train_step_parity"
        )
        assert out["world"] == 2
        assert out["divergence"] == 0.0

        # Single-process reference: same init, same data, full batch.
        from machine_learning_apache_spark_tpu.models import MLP
        from machine_learning_apache_spark_tpu.parallel.data_parallel import (
            params_fingerprint,
        )
        from machine_learning_apache_spark_tpu.train.losses import cross_entropy
        from machine_learning_apache_spark_tpu.train.state import (
            TrainState,
            make_optimizer,
        )

        rng = np.random.default_rng(0)
        feats = jnp.asarray(rng.normal(size=(16, 4)).astype(np.float32))
        labels = jnp.asarray(rng.integers(0, 3, 16).astype(np.int64))
        model = MLP(layers=(4, 5, 3))
        params = model.init(jax.random.key(0), jnp.ones((1, 4)))["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer("sgd", 0.1)
        )

        @jax.jit
        def step(state):
            def loss_fn(p):
                return cross_entropy(model.apply({"params": p}, feats), labels)

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            return state.apply_gradients(grads), loss

        expected_losses = []
        for _ in range(3):
            state, loss = step(state)
            expected_losses.append(float(loss))
        np.testing.assert_allclose(out["losses"], expected_losses, rtol=1e-5)
        np.testing.assert_allclose(
            out["fingerprint"], params_fingerprint(state.params), rtol=1e-5
        )


class TestFailureDetection:
    """The monitor/teardown layer's contract: every way a gang dies maps
    to a structured GangFailure (rank, cause, attempt), never a hang."""

    def test_nonzero_exit_structured_failure(self):
        from machine_learning_apache_spark_tpu.launcher import GangFailure

        with pytest.raises(GangFailure) as ei:
            Distributor(num_processes=2, platform="cpu", timeout=120).run(
                "launcher_workers:boom"
            )
        assert ei.value.cause == "exit"
        assert ei.value.attempt == 0
        assert ei.value.rank in (0, 1)
        assert "worker exploded" in str(ei.value)  # real traceback attached

    def test_gang_deadline_expiry(self):
        """Workers that never finish (but never die, and keep
        heartbeating) must be ended by the gang deadline — cause
        'deadline', no rank to blame."""
        from machine_learning_apache_spark_tpu.launcher import GangFailure

        with pytest.raises(GangFailure) as ei:
            Distributor(
                num_processes=2, platform="cpu", timeout=10, term_grace=1.0
            ).run("launcher_workers:sleep_forever")
        assert ei.value.cause == "deadline"
        assert ei.value.rank is None

    def test_restart_exhaustion_keeps_structured_fields(self):
        from machine_learning_apache_spark_tpu.launcher import GangFailure

        with pytest.raises(GangFailure) as ei:
            Distributor(
                num_processes=2, platform="cpu", timeout=120,
                max_restarts=1, backoff_base=0.05,
            ).run("launcher_workers:boom")
        assert ei.value.attempt == 1  # the exhausting (last) attempt

    def test_read_result_missing_file(self, tmp_path):
        r = Distributor._read_result(str(tmp_path / "absent.pkl"), rank=3)
        assert r.rank == 3
        assert "produced no result" in r.error

    def test_read_result_corrupt_file(self, tmp_path):
        p = tmp_path / "result_0.pkl"
        p.write_bytes(b"\x80\x04garbage")
        r = Distributor._read_result(str(p), rank=0)
        assert r.rank == 0
        assert "produced no result" in r.error  # unreadable == no result


class TestCommandsForHosts:
    def test_command_lines(self):
        cmds = Distributor(local_mode=False).commands_for_hosts(
            "launcher_workers:echo_rank", ["tpu-host-0", "tpu-host-1"]
        )
        assert len(cmds) == 2
        assert "--coordinator tpu-host-0:29500" in cmds[0]
        assert "--process-id 1" in cmds[1]

    def test_cluster_run_raises(self):
        with pytest.raises(RuntimeError, match="commands_for_hosts"):
            Distributor(local_mode=False).run("launcher_workers:echo_rank")

    @pytest.mark.slow
    def test_commands_execute_end_to_end(self):
        """The multi-host control plane, end to end: execute the LITERAL
        command strings from ``commands_for_hosts`` (2 "hosts" on loopback —
        the role spark-submit plays for ``distributed_cnn.py:227-231``),
        and assert both ranks rendezvous over the coordinator and agree on
        a cross-process collective sum. The scheduler's own contribution is
        environment only (PYTHONPATH + platform), never edited commands."""
        import os
        import shlex
        import subprocess
        import sys

        from machine_learning_apache_spark_tpu.launcher.distributor import (
            _free_port,
        )

        port = _free_port()
        cmds = Distributor(local_mode=False).commands_for_hosts(
            "launcher_workers:multihost_probe",
            ["127.0.0.1", "127.0.0.1"],
            coordinator_port=port,
        )
        env = {
            **os.environ,
            # Both forms, like Distributor._run_gang: the env var for vanilla
            # images, MLSPARK_PLATFORM for the runner's config-API override.
            "JAX_PLATFORMS": "cpu",
            "MLSPARK_PLATFORM": "cpu",
            "PYTHONPATH": os.pathsep.join(p for p in sys.path if p),
        }
        procs = [
            subprocess.Popen(
                shlex.split(c),
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
            for c in cmds
        ]
        outs = []
        try:
            for p in procs:
                outs.append(p.communicate(timeout=300))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for rank, (p, (out, err)) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {rank} failed:\n{err[-2000:]}"
            assert f"MULTIHOST_RESULT rank={rank} world=2 sum=3.0" in out, out



class TestObservabilityContracts:
    """The launcher half of the live plane: JSON heartbeat payloads, the
    ``telemetry_http`` knob, and the gang_status scraper end to end."""

    def test_heartbeat_payload_json_round_trip(self, tmp_path):
        import json
        import time

        from machine_learning_apache_spark_tpu.launcher.monitor import (
            read_heartbeat,
        )
        from machine_learning_apache_spark_tpu.launcher.runner import (
            _start_heartbeat,
        )
        from machine_learning_apache_spark_tpu.telemetry import events

        events.beacon_update(phase="train", step=7, http_port=9100)
        try:
            hb = tmp_path / "heartbeat_3"
            _start_heartbeat(str(hb), interval=0.05, rank=3)
            deadline = time.monotonic() + 10
            payload = {}
            while time.monotonic() < deadline:
                payload = read_heartbeat(str(hb))
                if payload.get("phase") == "train":
                    break
                time.sleep(0.02)
            assert payload["rank"] == 3
            assert payload["pid"] > 0 and "wall" in payload
            assert payload["phase"] == "train" and payload["step"] == 7
            assert payload["http_port"] == 9100
            # the beat is a valid single JSON document (atomic replace,
            # never a torn append)
            assert json.loads(hb.read_text()) == payload
        finally:
            events.reset()

    def test_read_heartbeat_tolerates_legacy_and_torn_files(self, tmp_path):
        from machine_learning_apache_spark_tpu.launcher.monitor import (
            read_heartbeat,
        )

        legacy = tmp_path / "heartbeat_0"
        legacy.touch()  # pre-JSON empty-touch beat
        assert read_heartbeat(str(legacy)) == {}
        torn = tmp_path / "heartbeat_1"
        torn.write_text('{"rank": 1, "phase"')
        assert read_heartbeat(str(torn)) == {}
        assert read_heartbeat(str(tmp_path / "absent")) == {}
        notdict = tmp_path / "heartbeat_2"
        notdict.write_text("[1, 2]")
        assert read_heartbeat(str(notdict)) == {}

    def test_telemetry_http_knob_validation(self):
        with pytest.raises(ValueError, match="telemetry_http"):
            Distributor(num_processes=2, telemetry_http=-1)
        with pytest.raises(ValueError, match="telemetry_http"):
            Distributor(num_processes=2, telemetry_http=70000)

    def test_telemetry_http_env_plumbing(self):
        out = Distributor(
            num_processes=2, platform="cpu", timeout=120, telemetry_http=0
        ).run("launcher_workers:echo_telemetry_http")
        assert out == {"telemetry_http": "0", "rank": 0}

    def test_explicit_env_wins_over_knob(self):
        # one spawned rank: a fixed port must not collide across ranks
        from machine_learning_apache_spark_tpu.launcher.distributor import (
            _free_port,
        )

        port = _free_port()
        out = Distributor(
            num_processes=1, platform="cpu", timeout=120, telemetry_http=0,
            env={"MLSPARK_TELEMETRY_HTTP": str(port)},
        ).run("launcher_workers:echo_telemetry_http")
        assert out["telemetry_http"] == str(port)

    def test_gang_status_smoke_subprocess(self):
        """tools/gang_status.py --smoke is the tier-1 CI entry for the
        scrape plane: a 2-rank gang with ephemeral HTTP ports, both ranks
        discovered via sidecars and scraped live."""
        import os
        import subprocess
        import sys

        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        r = subprocess.run(
            [
                sys.executable,
                os.path.join(repo_root, "tools", "gang_status.py"),
                "--smoke",
            ],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
        assert "smoke ok: scraped 2/2 ranks" in r.stdout
        assert "# Gang status" in r.stdout


class TestElasticShrinkPolicy:
    """The Distributor's permanent-loss judgment and shrink-to-fit path
    (docs/FAULT_TOLERANCE.md "Elastic resume"). Workers are plain
    functions — no jax gang — so these pin the POLICY; the end-to-end
    reshard-resume is drilled in TestElasticShrinkTraining and
    tools/fault_drill.py."""

    def test_budget_exhausted_names_rank_cause_attempts(self):
        from machine_learning_apache_spark_tpu.launcher import GangFailure

        with pytest.raises(GangFailure) as ei:
            Distributor(
                num_processes=2, platform="cpu", timeout=120,
                rank_restart_budget=0, backoff_base=0.05, term_grace=1.0,
            ).run("launcher_workers:fail_rank", 1)
        f = ei.value
        assert f.permanent is True
        assert f.rank == 1
        assert f.cause == "exit"
        msg = str(f)
        assert "permanently lost" in msg
        assert "budget 0" in msg
        assert "elastic" in msg  # tells the operator which knob to flip

    def test_no_budget_no_elastic_keeps_legacy_semantics(self):
        from machine_learning_apache_spark_tpu.launcher import GangFailure

        with pytest.raises(GangFailure) as ei:
            Distributor(
                num_processes=2, platform="cpu", timeout=120,
                max_restarts=1, backoff_base=0.05, term_grace=1.0,
            ).run("launcher_workers:fail_rank", 1)
        assert ei.value.permanent is False  # exhausted restarts, not a
        # permanent-loss judgment: nobody opted into the elastic policy

    def test_elastic_shrinks_past_lost_rank(self):
        """rank 2 always fails; with elastic on and budget 0 the gang
        must retry at world 2 — where the poisoned rank no longer exists
        — and succeed, with MLSPARK_ELASTIC plumbed to the workers."""
        out = Distributor(
            num_processes=3, platform="cpu", timeout=240, elastic=True,
            rank_restart_budget=0, elastic_min_world=1,
            backoff_base=0.05, term_grace=1.0,
        ).run("launcher_workers:fail_rank", 2)
        assert out["world"] == 2
        assert out["elastic_env"] == "1"

    def test_min_world_floor_raises_permanent(self):
        from machine_learning_apache_spark_tpu.launcher import GangFailure

        with pytest.raises(GangFailure) as ei:
            Distributor(
                num_processes=2, platform="cpu", timeout=120, elastic=True,
                rank_restart_budget=0, elastic_min_world=2,
                backoff_base=0.05, term_grace=1.0,
            ).run("launcher_workers:fail_rank", 1)
        f = ei.value
        assert f.permanent is True and f.rank == 1
        assert "elastic_min_world" in str(f)

    def test_constructor_validation(self):
        with pytest.raises(ValueError, match="elastic_min_world"):
            Distributor(num_processes=2, elastic_min_world=3)
        with pytest.raises(ValueError, match="elastic_min_world"):
            Distributor(num_processes=2, elastic_min_world=0)
        with pytest.raises(ValueError, match="rank_restart_budget"):
            Distributor(num_processes=2, rank_restart_budget=-1)


class TestElasticShrinkTraining:
    def test_shrink_resumes_training_from_group_checkpoint(
        self, tmp_path, monkeypatch
    ):
        """Small-config elastic_shrink drill (CI tier of the full
        tools/fault_drill.py scenario): a 3-rank ZeRO-1 gang loses rank
        2 permanently mid-training, shrinks to 2, reshards the 3-rank
        checkpoint group onto the 2-rank world, and finishes the
        remaining epochs — resumed from a checkpoint, not from scratch.
        (Loss parity vs an unfaulted run is asserted by the full drill,
        which this config mirrors at world 3.)"""
        import numpy as np

        from machine_learning_apache_spark_tpu.utils import faults

        monkeypatch.setenv(
            faults.ENV_PLAN, "crash@train_step:world=3,rank=2,step=5"
        )
        monkeypatch.setenv(faults.ENV_MARKER_DIR, str(tmp_path / "markers"))
        out = Distributor(
            num_processes=3, platform="cpu", timeout=480, elastic=True,
            rank_restart_budget=0, elastic_min_world=2,
            backoff_base=0.05, term_grace=2.0,
        ).run(
            "launcher_workers:elastic_drill_train", str(tmp_path / "gang"),
            epochs=4, global_batch=24, steps_per_epoch=2,
        )
        assert list((tmp_path / "markers").iterdir()), "fault never fired"
        assert out["world"] == 2
        # 8 total steps, checkpoints every epoch (2 steps), crash before
        # the 6th step: the shrunken gang resumes from the newest
        # group-durable checkpoint, never from scratch.
        assert out["resumed_step"] in (2, 4)
        assert np.isfinite(out["final_loss"])
