"""Core runtime tests: session, config, mesh, metrics, timing, prng."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import machine_learning_apache_spark_tpu as mlspark
from machine_learning_apache_spark_tpu.config import SessionConfig, TrainConfig
from machine_learning_apache_spark_tpu.parallel import (
    batch_sharding,
    data_parallel_mesh,
    make_mesh,
)
from machine_learning_apache_spark_tpu.parallel.mesh import shard_batch
from machine_learning_apache_spark_tpu.train.metrics import (
    Mean,
    MetricBundle,
    Sum,
    accuracy,
    logits_accuracy,
)
from machine_learning_apache_spark_tpu.utils import KeySeq, Timer, timed_span


def test_fake_cluster_has_8_devices():
    assert jax.device_count() == 8
    assert jax.default_backend() == "cpu"


class TestSession:
    def test_builder_get_or_create_is_singleton(self):
        s1 = mlspark.Session.builder.app_name("t").get_or_create()
        s2 = mlspark.Session.builder.get_or_create()
        assert s1 is s2
        s1.stop()

    def test_get_or_create_warns_only_on_differing_conf(self):
        """Idempotent re-creation with identical conf stays quiet; only
        keys that would actually change the active session warn (Spark
        semantics: builder conf is never applied to an existing session).
        The package logger doesn't propagate to root (it owns its stream
        handler), so capture with a handler attached to it directly."""
        import logging

        records: list[logging.LogRecord] = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record)

        session_log = logging.getLogger(
            "machine_learning_apache_spark_tpu.session"
        )
        cap = Capture(level=logging.WARNING)
        session_log.addHandler(cap)
        s = (
            mlspark.Session.builder.appName("warn-test")
            .config("spark.executor.instances", 4)
            .getOrCreate()
        )
        try:
            # Same conf (string value coerces to the active int) → quiet.
            mlspark.Session.builder.appName("warn-test").config(
                "spark.executor.instances", "4"
            ).getOrCreate()
            assert not [r for r in records if "ignored" in r.getMessage()]
            # Differing value → warns, naming only the differing key.
            mlspark.Session.builder.appName("warn-test").config(
                "spark.executor.instances", 8
            ).getOrCreate()
            warns = [r for r in records if "ignored" in r.getMessage()]
            assert warns and "executor_instances" in warns[0].getMessage()
            assert "app_name" not in warns[0].getMessage()
        finally:
            session_log.removeHandler(cap)
            s.stop()

    def test_spark_style_conf_keys(self):
        s = (
            mlspark.Session.builder.appName("conf-test")
            .config("spark.executor.instances", 4)
            .config("spark.executor.cores", 2)
            .getOrCreate()
        )
        assert s.conf.app_name == "conf-test"
        assert s.conf.executor_instances == 4
        assert s.conf.executor_cores == 2
        # world size derives from runtime, not conf (unlike distributed_cnn.py:43)
        assert s.executor_count == jax.process_count()
        assert s.device_count == 8
        s.stop()

    def test_compilation_cache_conf(self, tmp_path):
        """``spark.compilation.cache.dir`` conf: the session enables the
        persistent XLA cache, and a compiled program actually writes
        entries under the dir (reused by later processes — the startup
        lever for repeat runs on remote-controller topologies)."""
        import os

        d = str(tmp_path / "xla-cache")
        s = (
            mlspark.Session.builder.appName("cache-test")
            .config("spark.compilation.cache.dir", d)
            .getOrCreate()
        )
        try:
            assert s.conf.compilation_cache_dir == d
            assert os.path.isdir(d)
            # Force min-compile-time to 0 so this tiny program qualifies.
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.jit(lambda x: (x @ x.T).sum())(
                jnp.ones((64, 64))
            ).block_until_ready()
            entries = [f for _, _, fs in os.walk(d) for f in fs]
            assert entries, "no persistent cache entries written"
        finally:
            # Cache settings are process-global JAX config: restore ALL of
            # them or later tests silently run different cache semantics.
            from machine_learning_apache_spark_tpu.utils.compilation_cache import (
                disable_compilation_cache,
            )

            disable_compilation_cache()
            s.stop()

    def test_stop_clears_singleton(self):
        s = mlspark.Session.builder.get_or_create()
        s.stop()
        s2 = mlspark.Session.builder.get_or_create()
        assert s2 is not s
        s2.stop()


class TestConfig:
    def test_from_env_override(self, monkeypatch):
        monkeypatch.setenv("MLSPARK_BATCH_SIZE", "64")
        monkeypatch.setenv("MLSPARK_LEARNING_RATE", "0.5")
        cfg = TrainConfig.from_env()
        assert cfg.batch_size == 64
        assert cfg.learning_rate == 0.5

    def test_from_args(self):
        cfg = TrainConfig.from_args(["--epochs", "7", "--optimizer", "sgd"])
        assert cfg.epochs == 7
        assert cfg.optimizer == "sgd"

    def test_replace(self):
        cfg = SessionConfig().replace(app_name="x")
        assert cfg.app_name == "x"


class TestMesh:
    def test_default_data_parallel(self):
        mesh = data_parallel_mesh()
        assert mesh.shape == {"data": 8}

    def test_wildcard(self):
        mesh = make_mesh({"data": 0, "model": 2})
        assert mesh.shape["model"] == 2
        assert mesh.shape["data"] == 4

    def test_2d_mesh_axis_order(self):
        mesh = make_mesh({"model": 4, "data": 2})
        # data is the outer axis, model innermost (ICI locality)
        assert tuple(mesh.axis_names) == ("data", "model")

    def test_invalid_mesh_raises(self):
        with pytest.raises(ValueError):
            make_mesh({"data": 3})
        with pytest.raises(ValueError):
            make_mesh({"data": 0, "model": 0})

    def test_shard_batch_places_on_mesh(self):
        mesh = data_parallel_mesh()
        x = np.arange(32, dtype=np.float32).reshape(16, 2)
        sharded = shard_batch(mesh, {"x": x})["x"]
        assert sharded.sharding == batch_sharding(mesh)
        np.testing.assert_array_equal(np.asarray(sharded), x)


class TestMetrics:
    def test_accuracy_matches_reference_semantics(self):
        y = jnp.array([0, 1, 2, 2])
        p = jnp.array([0, 1, 1, 2])
        assert float(accuracy(y, p)) == 75.0

    def test_logits_accuracy(self):
        logits = jnp.array([[0.1, 0.9], [0.8, 0.2]])
        labels = jnp.array([1, 0])
        assert float(logits_accuracy(logits, labels)) == 100.0

    def test_accumulators(self):
        b = MetricBundle()
        for v in [1.0, 2.0, 3.0]:
            b.sum("total_loss").update(v)
            b.mean("avg_loss").update(v)
        out = b.compute()
        assert out["total_loss"] == 6.0
        assert out["avg_loss"] == 2.0
        assert "total_loss" in b.log_line()


class TestUtils:
    def test_keyseq_deterministic(self):
        a = KeySeq(0)
        b = KeySeq(0)
        assert jnp.array_equal(
            jax.random.key_data(a()), jax.random.key_data(b())
        )
        assert not jnp.array_equal(
            jax.random.key_data(a()), jax.random.key_data(b.fold(1)())
        )

    def test_timer_and_span(self, capsys):
        t = Timer("x").start()
        assert t.lap() >= 0.0
        with timed_span("Training Time"):
            pass
        out = capsys.readouterr().out
        assert "Training Time" in out and "sec" in out
