"""inference.Translator / inference.Classifier: raw-input prediction over
trained models, with save/load round-trips — the deployment story the
reference lacks (it trains and discards, quirk Q7 / SURVEY.md §5)."""

import jax
import numpy as np
import pytest

from machine_learning_apache_spark_tpu.inference import Classifier, Translator
from machine_learning_apache_spark_tpu.recipes.translation import train_translator


@pytest.fixture(scope="module")
def trained():
    """A translator trained well on the deterministic word→word synthetic
    task (each source word maps to exactly one target word)."""
    out = train_translator(
        epochs=6, synthetic_n=1024, batch_size=16, max_len=10,
        d_model=64, ffn_hidden=128, num_heads=4, dropout=0.0, log_every=0,
        use_mesh=False, seed=7,
        _return_translator=True,
    )
    return out["translator"], out


class TestTranslator:
    def test_translates_strings(self, trained):
        t, _ = trained
        from machine_learning_apache_spark_tpu.data.datasets import (
            synthetic_translation_pairs,
        )

        pairs = synthetic_translation_pairs(1024, min_len=3, max_len=6, seed=7)
        srcs = [s for s, _ in pairs[:8]]
        refs = [r for _, r in pairs[:8]]
        hyps = t(srcs)
        assert len(hyps) == 8 and all(isinstance(h, str) for h in hyps)
        # deterministic word-for-word task: a well-trained model emits the
        # exact target words for most positions
        correct = total = 0
        for hyp, ref in zip(hyps, refs):
            h, r = hyp.split(), ref.split()
            total += len(r)
            correct += sum(a == b for a, b in zip(h, r))
        assert correct / total > 0.6, (correct, total, hyps[:2], refs[:2])

    def test_methods_agree_on_shapes(self, trained):
        t, _ = trained
        srcs = ["one two three"]
        for method, kw in [
            ("greedy", {}),
            ("beam", {"beam_size": 3}),
            ("sample", {"temperature": 0.5, "top_k": 5, "rng": jax.random.key(0)}),
        ]:
            out = t(srcs, method=method, **kw)
            assert len(out) == 1 and isinstance(out[0], str)
        with pytest.raises(ValueError, match="method"):
            t(srcs, method="nope")
        with pytest.raises(ValueError, match="rng"):
            t(srcs, method="sample")  # silent fixed default would repeat

    def test_unregistered_tokenizer_fails_at_save(self, trained, tmp_path):
        """A pipeline built around a bare callable cannot be rebuilt by
        load(); save() must refuse up front, not persist an unloadable
        model."""
        from machine_learning_apache_spark_tpu.data.text import TextPipeline

        t, _ = trained
        broken = Translator(
            t.model, t.params,
            TextPipeline(t.src_pipe.vocab, lambda s: s.split(), max_seq_len=9),
            t.trg_pipe,
        )
        with pytest.raises(ValueError, match="not a registered name"):
            broken.save(str(tmp_path / "broken"))

    def test_save_load_round_trip(self, trained, tmp_path):
        t, _ = trained
        srcs = ["alpha beta gamma", "delta epsilon"]
        before = t(srcs)
        t.save(str(tmp_path / "model"))
        t2 = Translator.load(str(tmp_path / "model"))
        after = t2(srcs)
        assert before == after
        # vocab round-trips exactly, specials included
        assert t2.trg_pipe.vocab.itos == t.trg_pipe.vocab.itos
        assert t2.src_pipe.vocab["<unk>"] == t.src_pipe.vocab["<unk>"]
        # re-save over the same directory is a clean overwrite
        t2.save(str(tmp_path / "model"))
        assert Translator.load(str(tmp_path / "model"))(srcs) == before

    def test_shadowing_custom_tokenizer_refused(self, trained, tmp_path):
        """A custom callable whose __name__ collides with a registry key
        must not be silently swapped for the built-in on load."""
        from machine_learning_apache_spark_tpu.data.text import TextPipeline

        t, _ = trained

        def word_punct(s):  # shadows the registry name
            return s.split()

        broken = Translator(
            t.model, t.params,
            TextPipeline(t.src_pipe.vocab, word_punct, max_seq_len=9),
            t.trg_pipe,
        )
        with pytest.raises(ValueError, match="different callable"):
            broken.save(str(tmp_path / "shadow"))


class TestClassifier:
    def test_mlp_predict_and_round_trip(self, tmp_path):
        from machine_learning_apache_spark_tpu.data.datasets import (
            synthetic_multiclass,
        )
        from machine_learning_apache_spark_tpu.recipes.mlp import train_mlp

        # the sigmoid MLP at SGD(0.03) learns slowly: the known-good recipe
        # config (cf. TestMLPRecipe) reaches >55% at 250 epochs
        out = train_mlp(
            epochs=250, synthetic_n=480, batch_size=8, _return_classifier=True
        )
        clf = out["classifier"]
        frame = synthetic_multiclass(480, num_features=4, num_classes=3, seed=1234)
        feats, labels = frame.arrays()
        preds = np.asarray(clf.predict(feats))
        acc = (preds == np.asarray(labels)).mean() * 100
        # the classifier must track the recipe's own reported accuracy
        assert acc > out["accuracy"] - 10.0, (acc, out["accuracy"])
        assert acc > 50.0, acc
        probs = np.asarray(clf.predict_proba(feats[:5]))
        np.testing.assert_allclose(probs.sum(axis=-1), 1.0, rtol=1e-5)

        clf.save(str(tmp_path / "mlp"))
        clf2 = Classifier.load(str(tmp_path / "mlp"))
        np.testing.assert_array_equal(
            np.asarray(clf2.predict(feats[:20])), preds[:20]
        )

    def test_lstm_predicts_raw_strings(self, tmp_path):
        from machine_learning_apache_spark_tpu.data.datasets import (
            synthetic_text_classification,
        )
        from machine_learning_apache_spark_tpu.recipes.lstm import train_lstm

        out = train_lstm(
            epochs=2, synthetic_n=512, batch_size=16, max_seq_len=24,
            _return_classifier=True,
        )
        clf = out["classifier"]
        texts, labels = synthetic_text_classification(64, num_classes=4, seed=0)
        preds = np.asarray(clf.predict(texts))  # raw strings in
        assert preds.shape == (64,)
        acc = (preds == np.asarray(labels)).mean() * 100
        assert acc > 30.0, acc  # beats 4-class chance

        clf.save(str(tmp_path / "lstm"))
        clf2 = Classifier.load(str(tmp_path / "lstm"))
        np.testing.assert_array_equal(
            np.asarray(clf2.predict(texts[:10])), preds[:10]
        )
        assert clf2.last_timestep and clf2.pipeline is not None

    def test_cnn_classifier_batched(self):
        from machine_learning_apache_spark_tpu.recipes.cnn import train_cnn

        out = train_cnn(
            epochs=1, synthetic_n=256, batch_size=16, hidden_units=4,
            _return_classifier=True,
        )
        clf = out["classifier"]
        clf.batch_size = 100  # forces a ragged chunked predict
        x = np.random.default_rng(0).normal(size=(256, 28, 28, 1)).astype("float32")
        assert np.asarray(clf.predict(x)).shape == (256,)


class TestRegisteredCustomTokenizer:
    def test_registered_custom_tokenizer_persists(self, trained, tmp_path):
        """register_tokenizer closes the loop the save() errors point to: a
        custom tokenizer registered under its own name saves and loads."""
        from machine_learning_apache_spark_tpu.data.text import (
            TextPipeline,
            register_tokenizer,
        )

        def upper_split(s):
            return s.upper().split()

        register_tokenizer("upper_split_test", upper_split)
        try:
            t, _ = trained
            custom = Translator(
                t.model, t.params,
                TextPipeline(
                    t.src_pipe.vocab, "upper_split_test", max_seq_len=9,
                    fixed_len=10,
                ),
                t.trg_pipe,
            )
            custom.save(str(tmp_path / "custom"))
            loaded = Translator.load(str(tmp_path / "custom"))
            assert loaded.src_pipe.tokenizer is upper_split
            assert loaded(["a b"]) == custom(["a b"])
        finally:
            from machine_learning_apache_spark_tpu.data import text

            text._TOKENIZERS.pop("upper_split_test", None)

    def test_shadowing_builtin_requires_overwrite(self):
        import pytest as _pytest

        from machine_learning_apache_spark_tpu.data.text import (
            register_tokenizer,
        )

        with _pytest.raises(ValueError, match="already registered"):
            register_tokenizer("word_punct", lambda s: s.split())
        with _pytest.raises(TypeError, match="callable"):
            register_tokenizer("not_fn", 42)

    def test_custom_tokenizer_fresh_process_round_trip(self, trained, tmp_path):
        """The full spacy-seam contract (``pytorch_machine_translator.py:20-21``):
        a custom tokenizer registered under its own name → ``save`` → a FRESH
        python process re-registers the name, ``load``s, and produces
        identical translations. Same-process reload (above) can hide registry
        state leaking through module globals; a subprocess cannot."""
        import json as _json
        import os
        import subprocess
        import sys

        from machine_learning_apache_spark_tpu.data.text import (
            TextPipeline,
            register_tokenizer,
        )

        def upper_split(s):
            return s.upper().split()

        register_tokenizer("upper_split_fresh", upper_split)
        try:
            t, _ = trained
            custom = Translator(
                t.model, t.params,
                TextPipeline(
                    t.src_pipe.vocab, "upper_split_fresh", max_seq_len=9,
                    fixed_len=10,
                ),
                t.trg_pipe,
            )
            model_dir = str(tmp_path / "fresh")
            custom.save(model_dir)
            srcs = ["alpha beta gamma", "delta epsilon"]
            before = custom(srcs)

            repo_root = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            )
            env = {
                **os.environ,
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": repo_root
                + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
            }
            # The hosting image may pre-import jax from sitecustomize, so the
            # children also force the platform via the config API.
            preamble = (
                "import jax\n"
                "jax.config.update('jax_platforms', 'cpu')\n"
            )
            child = preamble + f"""
import json
from machine_learning_apache_spark_tpu.data.text import register_tokenizer
from machine_learning_apache_spark_tpu.inference import Translator

def upper_split(s):
    return s.upper().split()

register_tokenizer("upper_split_fresh", upper_split)
loaded = Translator.load({model_dir!r})
assert loaded.src_pipe.tokenizer is upper_split
print("RESULT:" + json.dumps(loaded({srcs!r})))
"""
            proc = subprocess.run(
                [sys.executable, "-c", child],
                capture_output=True, text=True, timeout=600,
                cwd=str(tmp_path), env=env,
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            line = [
                l for l in proc.stdout.splitlines() if l.startswith("RESULT:")
            ][0]
            assert _json.loads(line[len("RESULT:"):]) == before

            # Without the re-registration the load must fail loudly (the
            # recorded name cannot resolve), not silently mistokenize.
            bad = subprocess.run(
                [
                    sys.executable, "-c",
                    preamble
                    + "from machine_learning_apache_spark_tpu.inference "
                    "import Translator\n"
                    f"Translator.load({model_dir!r})",
                ],
                capture_output=True, text=True, timeout=600,
                cwd=str(tmp_path), env=env,
            )
            assert bad.returncode != 0
            assert "upper_split_fresh" in bad.stderr
        finally:
            from machine_learning_apache_spark_tpu.data import text

            text._TOKENIZERS.pop("upper_split_fresh", None)
