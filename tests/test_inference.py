"""inference.Translator: raw-string translation over a trained model, with
save/load round-trip — the deployment story the reference lacks (it trains
and discards, quirk Q7 / SURVEY.md §5)."""

import jax
import numpy as np
import pytest

from machine_learning_apache_spark_tpu.inference import Translator
from machine_learning_apache_spark_tpu.recipes.translation import train_translator


@pytest.fixture(scope="module")
def trained():
    """A translator trained well on the deterministic word→word synthetic
    task (each source word maps to exactly one target word)."""
    out = train_translator(
        epochs=6, synthetic_n=1024, batch_size=16, max_len=10,
        d_model=64, ffn_hidden=128, num_heads=4, dropout=0.0, log_every=0,
        use_mesh=False, seed=7,
        _return_translator=True,
    )
    return out["translator"], out


class TestTranslator:
    def test_translates_strings(self, trained):
        t, _ = trained
        from machine_learning_apache_spark_tpu.data.datasets import (
            synthetic_translation_pairs,
        )

        pairs = synthetic_translation_pairs(1024, min_len=3, max_len=6, seed=7)
        srcs = [s for s, _ in pairs[:8]]
        refs = [r for _, r in pairs[:8]]
        hyps = t(srcs)
        assert len(hyps) == 8 and all(isinstance(h, str) for h in hyps)
        # deterministic word-for-word task: a well-trained model emits the
        # exact target words for most positions
        correct = total = 0
        for hyp, ref in zip(hyps, refs):
            h, r = hyp.split(), ref.split()
            total += len(r)
            correct += sum(a == b for a, b in zip(h, r))
        assert correct / total > 0.6, (correct, total, hyps[:2], refs[:2])

    def test_methods_agree_on_shapes(self, trained):
        t, _ = trained
        srcs = ["one two three"]
        for method, kw in [
            ("greedy", {}),
            ("beam", {"beam_size": 3}),
            ("sample", {"temperature": 0.5, "top_k": 5, "rng": jax.random.key(0)}),
        ]:
            out = t(srcs, method=method, **kw)
            assert len(out) == 1 and isinstance(out[0], str)
        with pytest.raises(ValueError, match="method"):
            t(srcs, method="nope")
        with pytest.raises(ValueError, match="rng"):
            t(srcs, method="sample")  # silent fixed default would repeat

    def test_unregistered_tokenizer_fails_at_save(self, trained, tmp_path):
        """A pipeline built around a bare callable cannot be rebuilt by
        load(); save() must refuse up front, not persist an unloadable
        model."""
        from machine_learning_apache_spark_tpu.data.text import TextPipeline

        t, _ = trained
        broken = Translator(
            t.model, t.params,
            TextPipeline(t.src_pipe.vocab, lambda s: s.split(), max_seq_len=9),
            t.trg_pipe,
        )
        with pytest.raises(ValueError, match="not a registered name"):
            broken.save(str(tmp_path / "broken"))

    def test_save_load_round_trip(self, trained, tmp_path):
        t, _ = trained
        srcs = ["alpha beta gamma", "delta epsilon"]
        before = t(srcs)
        t.save(str(tmp_path / "model"))
        t2 = Translator.load(str(tmp_path / "model"))
        after = t2(srcs)
        assert before == after
        # vocab round-trips exactly, specials included
        assert t2.trg_pipe.vocab.itos == t.trg_pipe.vocab.itos
        assert t2.src_pipe.vocab["<unk>"] == t.src_pipe.vocab["<unk>"]
        # re-save over the same directory is a clean overwrite
        t2.save(str(tmp_path / "model"))
        assert Translator.load(str(tmp_path / "model"))(srcs) == before

    def test_shadowing_custom_tokenizer_refused(self, trained, tmp_path):
        """A custom callable whose __name__ collides with a registry key
        must not be silently swapped for the built-in on load."""
        from machine_learning_apache_spark_tpu.data.text import TextPipeline

        t, _ = trained

        def word_punct(s):  # shadows the registry name
            return s.split()

        broken = Translator(
            t.model, t.params,
            TextPipeline(t.src_pipe.vocab, word_punct, max_seq_len=9),
            t.trg_pipe,
        )
        with pytest.raises(ValueError, match="different callable"):
            broken.save(str(tmp_path / "shadow"))
