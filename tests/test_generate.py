"""Greedy decoding tests: output contract with arbitrary params, and the
end-to-end property the reference never checks (it trains and discards —
quirk Q7): a model trained on the deterministic synthetic word-for-word
translation task actually translates."""

import jax
import jax.numpy as jnp
import numpy as np

from machine_learning_apache_spark_tpu.data import ArrayDataset
from machine_learning_apache_spark_tpu.data.datasets import (
    synthetic_translation_pairs,
)
from machine_learning_apache_spark_tpu.data.text import (
    EOS_ID,
    PAD_ID,
    SOS_ID,
    translation_pipelines,
)
from machine_learning_apache_spark_tpu.models import (
    Transformer,
    TransformerConfig,
    greedy_translate,
    greedy_translate_cached,
)


def tiny_model(max_len=16, vocab=64):
    cfg = TransformerConfig(
        src_vocab_size=vocab,
        trg_vocab_size=vocab,
        d_model=32,
        ffn_hidden=64,
        num_heads=4,
        num_layers=1,
        max_len=max_len,
    )
    return Transformer(cfg)


class TestContract:
    def test_shape_sos_and_pad_after_eos(self):
        model = tiny_model()
        src = jnp.full((3, 10), 5, jnp.int32)
        params = model.init(
            jax.random.key(0), src, jnp.full((3, 8), 6, jnp.int32)
        )["params"]
        out = np.asarray(greedy_translate(model, params, src, max_new_tokens=12))
        assert out.shape == (3, 13)  # 12 generated + the sos slot
        assert (out[:, 0] == SOS_ID).all()
        # after the first eos in a row, everything is pad
        for row in out:
            eos_pos = np.flatnonzero(row == EOS_ID)
            if eos_pos.size:
                assert (row[eos_pos[0] + 1 :] == PAD_ID).all()

    def test_jittable(self):
        model = tiny_model()
        src = jnp.full((2, 10), 5, jnp.int32)
        params = model.init(
            jax.random.key(0), src, jnp.full((2, 8), 6, jnp.int32)
        )["params"]
        f = jax.jit(
            lambda p, s: greedy_translate(model, p, s, max_new_tokens=8)
        )
        assert f(params, src).shape == (2, 9)

    def test_zero_tokens_rejected(self):
        model = tiny_model()
        src = jnp.full((1, 4), 5, jnp.int32)
        params = model.init(
            jax.random.key(0), src, jnp.full((1, 4), 6, jnp.int32)
        )["params"]
        import pytest

        with pytest.raises(ValueError, match="max_new_tokens"):
            greedy_translate(model, params, src, max_new_tokens=0)


class TestLearnsToTranslate:
    def test_trained_model_translates(self):
        """Train briefly on the deterministic word→word synthetic task, then
        greedy-decode held-out sources and check token accuracy beats chance
        by a wide margin."""
        from machine_learning_apache_spark_tpu.recipes._common import make_loaders
        from machine_learning_apache_spark_tpu.recipes.translation import (
            make_translation_loss,
        )
        from machine_learning_apache_spark_tpu.train.loop import fit
        from machine_learning_apache_spark_tpu.train.state import (
            TrainState,
            make_optimizer,
        )

        pairs = synthetic_translation_pairs(1024, min_len=3, max_len=6, seed=7)
        src_pipe, trg_pipe = translation_pipelines(pairs, max_len=10)
        src = src_pipe([s for s, _ in pairs])
        trg = trg_pipe([t for _, t in pairs])

        cfg = TransformerConfig(
            src_vocab_size=len(src_pipe.vocab),
            trg_vocab_size=len(trg_pipe.vocab),
            d_model=64,
            ffn_hidden=128,
            num_heads=4,
            num_layers=1,
            dropout=0.0,
            max_len=10,
        )
        model = Transformer(cfg)
        params = model.init(
            jax.random.key(0), jnp.asarray(src[:2]), jnp.asarray(trg[:2, :-1])
        )["params"]
        state = TrainState.create(
            apply_fn=model.apply,
            params=params,
            tx=make_optimizer("adam", 3e-3),
        )
        loader, _ = make_loaders(
            ArrayDataset(src, trg), None, batch_size=16, mesh=None
        )
        result = fit(
            state,
            make_translation_loss(model, cfg.pad_id),
            loader,
            epochs=6,
            log_every=0,
        )

        held_src = jnp.asarray(src[:32])
        held_trg = np.asarray(trg[:32])
        decoded = np.asarray(
            greedy_translate(model, result.state.params, held_src,
                             max_new_tokens=9)  # buffer width 10 == trg width
        )
        # token accuracy over real (non-pad, non-sos) target positions
        target = held_trg[:, 1:]
        pred = decoded[:, 1:]
        real = target != PAD_ID
        acc = (pred[real] == target[real]).mean()
        assert acc > 0.5, f"decode accuracy {acc:.2f} — model did not learn"

        # The KV-cache decoder must reproduce the naive decoder exactly on
        # a trained (non-degenerate) model.
        cached = np.asarray(
            greedy_translate_cached(
                model, result.state.params, held_src, max_new_tokens=9
            )
        )
        np.testing.assert_array_equal(cached, decoded)


class TestCachedDecoder:
    def test_matches_naive_random_params(self):
        model = tiny_model(max_len=16)
        src = jnp.asarray(
            np.random.default_rng(3).integers(4, 60, (3, 10)), jnp.int32
        )
        params = model.init(
            jax.random.key(1), src, jnp.ones((3, 8), jnp.int32)
        )["params"]
        naive = greedy_translate(model, params, src, max_new_tokens=12)
        cached = greedy_translate_cached(model, params, src, max_new_tokens=12)
        np.testing.assert_array_equal(np.asarray(cached), np.asarray(naive))

    def test_bounds_validated(self):
        model = tiny_model(max_len=8)
        src = jnp.full((1, 4), 5, jnp.int32)
        params = model.init(
            jax.random.key(0), src, jnp.full((1, 4), 6, jnp.int32)
        )["params"]
        import pytest

        with pytest.raises(ValueError, match="max_new_tokens"):
            greedy_translate_cached(model, params, src, max_new_tokens=8)
