"""Greedy decoding tests: output contract with arbitrary params, and the
end-to-end property the reference never checks (it trains and discards —
quirk Q7): a model trained on the deterministic synthetic word-for-word
translation task actually translates."""

import jax
import jax.numpy as jnp
import numpy as np

from machine_learning_apache_spark_tpu.data import ArrayDataset
from machine_learning_apache_spark_tpu.data.datasets import (
    synthetic_translation_pairs,
)
from machine_learning_apache_spark_tpu.data.text import (
    EOS_ID,
    PAD_ID,
    SOS_ID,
    translation_pipelines,
)
from machine_learning_apache_spark_tpu.models import (
    Transformer,
    TransformerConfig,
    greedy_translate,
    greedy_translate_cached,
)


def tiny_model(max_len=16, vocab=64):
    cfg = TransformerConfig(
        src_vocab_size=vocab,
        trg_vocab_size=vocab,
        d_model=32,
        ffn_hidden=64,
        num_heads=4,
        num_layers=1,
        max_len=max_len,
    )
    return Transformer(cfg)


class TestContract:
    def test_shape_sos_and_pad_after_eos(self):
        model = tiny_model()
        src = jnp.full((3, 10), 5, jnp.int32)
        params = model.init(
            jax.random.key(0), src, jnp.full((3, 8), 6, jnp.int32)
        )["params"]
        out = np.asarray(greedy_translate(model, params, src, max_new_tokens=12))
        assert out.shape == (3, 13)  # 12 generated + the sos slot
        assert (out[:, 0] == SOS_ID).all()
        # after the first eos in a row, everything is pad
        for row in out:
            eos_pos = np.flatnonzero(row == EOS_ID)
            if eos_pos.size:
                assert (row[eos_pos[0] + 1 :] == PAD_ID).all()

    def test_jittable(self):
        model = tiny_model()
        src = jnp.full((2, 10), 5, jnp.int32)
        params = model.init(
            jax.random.key(0), src, jnp.full((2, 8), 6, jnp.int32)
        )["params"]
        f = jax.jit(
            lambda p, s: greedy_translate(model, p, s, max_new_tokens=8)
        )
        assert f(params, src).shape == (2, 9)

    def test_zero_tokens_rejected(self):
        model = tiny_model()
        src = jnp.full((1, 4), 5, jnp.int32)
        params = model.init(
            jax.random.key(0), src, jnp.full((1, 4), 6, jnp.int32)
        )["params"]
        import pytest

        with pytest.raises(ValueError, match="max_new_tokens"):
            greedy_translate(model, params, src, max_new_tokens=0)


class TestLearnsToTranslate:
    def test_trained_model_translates(self):
        """Train briefly on the deterministic word→word synthetic task, then
        greedy-decode held-out sources and check token accuracy beats chance
        by a wide margin."""
        from machine_learning_apache_spark_tpu.recipes._common import make_loaders
        from machine_learning_apache_spark_tpu.recipes.translation import (
            make_translation_loss,
        )
        from machine_learning_apache_spark_tpu.train.loop import fit
        from machine_learning_apache_spark_tpu.train.state import (
            TrainState,
            make_optimizer,
        )

        pairs = synthetic_translation_pairs(1024, min_len=3, max_len=6, seed=7)
        src_pipe, trg_pipe = translation_pipelines(pairs, max_len=10)
        src = src_pipe([s for s, _ in pairs])
        trg = trg_pipe([t for _, t in pairs])

        cfg = TransformerConfig(
            src_vocab_size=len(src_pipe.vocab),
            trg_vocab_size=len(trg_pipe.vocab),
            d_model=64,
            ffn_hidden=128,
            num_heads=4,
            num_layers=1,
            dropout=0.0,
            max_len=10,
        )
        model = Transformer(cfg)
        params = model.init(
            jax.random.key(0), jnp.asarray(src[:2]), jnp.asarray(trg[:2, :-1])
        )["params"]
        state = TrainState.create(
            apply_fn=model.apply,
            params=params,
            tx=make_optimizer("adam", 3e-3),
        )
        loader, _ = make_loaders(
            ArrayDataset(src, trg), None, batch_size=16, mesh=None
        )
        result = fit(
            state,
            make_translation_loss(model, cfg.pad_id),
            loader,
            epochs=6,
            log_every=0,
        )

        held_src = jnp.asarray(src[:32])
        held_trg = np.asarray(trg[:32])
        decoded = np.asarray(
            greedy_translate(model, result.state.params, held_src,
                             max_new_tokens=9)  # buffer width 10 == trg width
        )
        # token accuracy over real (non-pad, non-sos) target positions
        target = held_trg[:, 1:]
        pred = decoded[:, 1:]
        real = target != PAD_ID
        acc = (pred[real] == target[real]).mean()
        assert acc > 0.5, f"decode accuracy {acc:.2f} — model did not learn"

        # The KV-cache decoder must reproduce the naive decoder exactly on
        # a trained (non-degenerate) model.
        cached = np.asarray(
            greedy_translate_cached(
                model, result.state.params, held_src, max_new_tokens=9
            )
        )
        np.testing.assert_array_equal(cached, decoded)


class TestCachedDecoder:
    def test_matches_naive_random_params(self):
        model = tiny_model(max_len=16)
        src = jnp.asarray(
            np.random.default_rng(3).integers(4, 60, (3, 10)), jnp.int32
        )
        params = model.init(
            jax.random.key(1), src, jnp.ones((3, 8), jnp.int32)
        )["params"]
        naive = greedy_translate(model, params, src, max_new_tokens=12)
        cached = greedy_translate_cached(model, params, src, max_new_tokens=12)
        np.testing.assert_array_equal(np.asarray(cached), np.asarray(naive))

    def test_bounds_validated(self):
        model = tiny_model(max_len=8)
        src = jnp.full((1, 4), 5, jnp.int32)
        params = model.init(
            jax.random.key(0), src, jnp.full((1, 4), 6, jnp.int32)
        )["params"]
        import pytest

        with pytest.raises(ValueError, match="max_new_tokens"):
            greedy_translate_cached(model, params, src, max_new_tokens=8)


class TestSampling:
    """sample_translate: temperature / top-k / nucleus decoding over the same
    KV-cache step as the greedy decoder."""

    def _setup(self, seed=3, b=3):
        model = tiny_model(max_len=16)
        src = jnp.asarray(
            np.random.default_rng(seed).integers(4, 60, (b, 10)), jnp.int32
        )
        params = model.init(
            jax.random.key(1), src, jnp.ones((b, 8), jnp.int32)
        )["params"]
        return model, params, src

    def test_temperature_zero_equals_greedy(self):
        from machine_learning_apache_spark_tpu.models import sample_translate

        model, params, src = self._setup()
        greedy = greedy_translate_cached(model, params, src, max_new_tokens=12)
        sampled = sample_translate(
            model, params, src, jax.random.key(0),
            max_new_tokens=12, temperature=0.0,
        )
        np.testing.assert_array_equal(np.asarray(sampled), np.asarray(greedy))

    def test_top_k_1_equals_greedy(self):
        from machine_learning_apache_spark_tpu.models import sample_translate

        model, params, src = self._setup()
        greedy = greedy_translate_cached(model, params, src, max_new_tokens=12)
        sampled = sample_translate(
            model, params, src, jax.random.key(0),
            max_new_tokens=12, temperature=1.0, top_k=1,
        )
        np.testing.assert_array_equal(np.asarray(sampled), np.asarray(greedy))

    def test_contract_and_determinism_per_key(self):
        from machine_learning_apache_spark_tpu.models import sample_translate

        model, params, src = self._setup()
        a = sample_translate(
            model, params, src, jax.random.key(7),
            max_new_tokens=12, temperature=1.0, top_p=0.9,
        )
        b = sample_translate(
            model, params, src, jax.random.key(7),
            max_new_tokens=12, temperature=1.0, top_p=0.9,
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        out = np.asarray(a)
        assert out.shape == (3, 13)
        assert (out[:, 0] == SOS_ID).all()
        assert (out < model.cfg.trg_vocab_size).all() and (out >= 0).all()
        for row in out:
            eos_pos = np.flatnonzero(row == EOS_ID)
            if eos_pos.size:
                assert (row[eos_pos[0] + 1 :] == PAD_ID).all()

    def test_filter_logits_top_k_top_p(self):
        from machine_learning_apache_spark_tpu.models.transformer import (
            _filter_logits,
        )
        from machine_learning_apache_spark_tpu.ops.attention import NEG_INF

        logits = jnp.asarray([[3.0, 2.0, 1.0, 0.0, -1.0]])
        k2 = np.asarray(_filter_logits(logits, 1.0, 2, None))
        assert (k2[0, 2:] <= NEG_INF / 2).all()
        assert k2[0, 0] == 3.0 and k2[0, 1] == 2.0
        # top_p: softmax([3,2,1,0,-1]) ≈ [.64,.24,.09,.03,.01];
        # exclusive cum [.0,.64,.87,.96,.99] → p=0.7 keeps the first two.
        p = np.asarray(_filter_logits(logits, 1.0, None, 0.7))
        assert (p[0, 2:] <= NEG_INF / 2).all()
        assert p[0, 0] == 3.0 and p[0, 1] == 2.0
        # p→tiny still keeps the argmax
        tiny = np.asarray(_filter_logits(logits, 1.0, None, 1e-6))
        assert tiny[0, 0] == 3.0
        assert (tiny[0, 1:] <= NEG_INF / 2).all()

    def test_validation(self):
        import pytest

        from machine_learning_apache_spark_tpu.models import sample_translate

        model, params, src = self._setup(b=1)
        with pytest.raises(ValueError, match="top_k"):
            sample_translate(
                model, params, src, jax.random.key(0), top_k=0,
                max_new_tokens=4,
            )
        with pytest.raises(ValueError, match="top_p"):
            sample_translate(
                model, params, src, jax.random.key(0), top_p=1.5,
                max_new_tokens=4,
            )
        # greedy mode (temperature=0) rejects bad filter args identically
        with pytest.raises(ValueError, match="top_k"):
            sample_translate(
                model, params, src, jax.random.key(0), temperature=0.0,
                top_k=0, max_new_tokens=4,
            )
        # top_k >= vocab is a no-op filter, not an error
        out = sample_translate(
            model, params, src, jax.random.key(0),
            top_k=10 * model.cfg.trg_vocab_size, max_new_tokens=4,
        )
        assert out.shape == (1, 5)


class TestBleu:
    """corpus_bleu + strip_special_ids — the MT quality metric the reference
    never computes (loss only, ``pytorch_machine_translator.py:189``)."""

    def test_perfect_match_is_one(self):
        from machine_learning_apache_spark_tpu.train.metrics import corpus_bleu

        seqs = [[5, 6, 7, 8, 9], [4, 4, 5, 6, 7, 8]]
        assert corpus_bleu(seqs, seqs) == 1.0

    def test_known_value(self):
        from machine_learning_apache_spark_tpu.train.metrics import corpus_bleu

        # cand/ref share 3/4 unigrams, 2/3 bigrams, 1/2 trigrams, 0/1 4-grams
        cand = [[1, 2, 3, 9]]
        ref = [[1, 2, 3, 4]]
        # smoothed p4 = 1/(2*1); geometric mean of [3/4, 2/3, 1/2, 1/2]
        import math

        expected = math.exp(
            (math.log(3 / 4) + math.log(2 / 3) + math.log(1 / 2)
             + math.log(1 / 2)) / 4
        )
        np.testing.assert_allclose(
            corpus_bleu(cand, ref), expected, rtol=1e-9
        )

    def test_brevity_penalty(self):
        from machine_learning_apache_spark_tpu.train.metrics import corpus_bleu

        # candidate is a perfect prefix but half the reference length
        cand = [[1, 2, 3]]
        ref = [[1, 2, 3, 4, 5, 6]]
        score = corpus_bleu(cand, ref, max_n=2, smooth=False)
        import math

        assert score <= math.exp(1 - 6 / 3) + 1e-9

    def test_mismatched_lengths_raise(self):
        import pytest

        from machine_learning_apache_spark_tpu.train.metrics import corpus_bleu

        with pytest.raises(ValueError):
            corpus_bleu([[1]], [[1], [2]])

    def test_strip_special_ids(self):
        from machine_learning_apache_spark_tpu.train.metrics import (
            strip_special_ids,
        )

        rows = np.asarray([
            [SOS_ID, 5, 6, EOS_ID, PAD_ID, PAD_ID],
            [SOS_ID, 7, PAD_ID, 8, PAD_ID, PAD_ID],  # no eos: pads dropped
        ])
        assert strip_special_ids(rows) == [[5, 6], [7, 8]]

    def test_recipe_reports_bleu(self):
        from machine_learning_apache_spark_tpu.recipes.translation import (
            train_translator,
        )

        out = train_translator(
            epochs=1, synthetic_n=128, batch_size=8, max_len=16,
            d_model=32, ffn_hidden=64, num_heads=4, log_every=0,
            compute_bleu=True,
        )
        assert 0.0 <= out["bleu"] <= 1.0


class TestBeamSearch:
    """beam_translate: flat-batched KV-cache beam search (beyond-reference
    inference; the reference ships no decoding at all)."""

    def _setup(self, seed=3, b=3):
        model = tiny_model(max_len=16)
        src = jnp.asarray(
            np.random.default_rng(seed).integers(4, 60, (b, 10)), jnp.int32
        )
        params = model.init(
            jax.random.key(1), src, jnp.ones((b, 8), jnp.int32)
        )["params"]
        return model, params, src

    def _seq_logprob(self, model, params, src, ys):
        """Teacher-forced log-prob of the generated tokens (pad-masked)."""
        logits = model.apply(
            {"params": params}, src, ys[:, :-1], deterministic=True
        ).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok = ys[:, 1:]
        picked = jnp.take_along_axis(logp, tok[:, :, None], axis=-1)[..., 0]
        mask = tok != PAD_ID
        return np.asarray((picked * mask).sum(axis=-1))

    def test_beam1_equals_greedy(self):
        from machine_learning_apache_spark_tpu.models.transformer import (
            beam_translate,
        )

        model, params, src = self._setup()
        greedy = greedy_translate_cached(model, params, src, max_new_tokens=12)
        beam1 = beam_translate(
            model, params, src, beam_size=1, max_new_tokens=12,
            length_penalty=0.0,
        )
        np.testing.assert_array_equal(np.asarray(beam1), np.asarray(greedy))

    def test_contract_shape_sos_pad_after_eos(self):
        from machine_learning_apache_spark_tpu.models.transformer import (
            beam_translate,
        )

        model, params, src = self._setup()
        out = np.asarray(
            beam_translate(model, params, src, beam_size=4, max_new_tokens=12)
        )
        assert out.shape == (3, 13)
        assert (out[:, 0] == SOS_ID).all()
        for row in out:
            eos_pos = np.flatnonzero(row == EOS_ID)
            if eos_pos.size:
                assert (row[eos_pos[0] + 1 :] == PAD_ID).all()

    def test_beam4_not_worse_than_beam1_on_finished_rows(self):
        """NOT a universal invariant of beam search (the greedy path can be
        pruned mid-search), but a sanity bar: on rows where BOTH decoders
        return a finished (eos-terminated) hypothesis, beam-4's banked best
        finished hypothesis scores >= beam-1's under the same alpha=0
        scoring — beam-1's finished hypotheses are a subset of the
        candidates beam-4 banks. Rows where either is unfinished are
        skipped."""
        from machine_learning_apache_spark_tpu.models.transformer import (
            beam_translate,
        )

        model, params, src = self._setup()
        beam1 = beam_translate(
            model, params, src, beam_size=1, max_new_tokens=12,
            length_penalty=0.0,
        )
        beam4 = beam_translate(
            model, params, src, beam_size=4, max_new_tokens=12,
            length_penalty=0.0,
        )
        both_finished = np.asarray(
            (jnp.asarray(beam1) == EOS_ID).any(axis=1)
            & (jnp.asarray(beam4) == EOS_ID).any(axis=1)
        )
        if not both_finished.any():
            return  # nothing comparable this seed; other tests cover shape
        lp1 = self._seq_logprob(model, params, src, jnp.asarray(beam1))
        lp4 = self._seq_logprob(model, params, src, jnp.asarray(beam4))
        assert (lp4[both_finished] >= lp1[both_finished] - 1e-4).all(), (
            lp4, lp1,
        )

    def test_finished_hypothesis_preferred_and_never_lost(self):
        """A hypothesis that finishes is banked at that step: whenever any
        beam ever emitted eos, the returned row must be eos-terminated even
        if raw-score top-k later evicted that beam from the live set."""
        from machine_learning_apache_spark_tpu.models.transformer import (
            beam_translate,
        )

        # A handful of seeds to make at least one finishing row likely.
        for seed in range(4):
            model, params, src = self._setup(seed=seed)
            out = np.asarray(
                beam_translate(model, params, src, beam_size=4, max_new_tokens=12)
            )
            for row in out:
                eos_pos = np.flatnonzero(row == EOS_ID)
                if eos_pos.size:
                    # banked rows are well-formed: sos, content, eos, pads
                    assert row[0] == SOS_ID
                    assert (row[eos_pos[0] + 1 :] == PAD_ID).all()

    def test_validation(self):
        import pytest

        from machine_learning_apache_spark_tpu.models.transformer import (
            beam_translate,
        )

        model, params, src = self._setup(b=1)
        with pytest.raises(ValueError, match="beam_size"):
            beam_translate(model, params, src, beam_size=0)
        with pytest.raises(ValueError, match="max_new_tokens"):
            beam_translate(model, params, src, max_new_tokens=16)
