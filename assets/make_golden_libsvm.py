"""Regenerate the golden 150-row libsvm sample (deterministic).

The reference's C1/C3 data contract is Spark's
``sample_multiclass_classification_data.txt`` — 150 rows, 4 features scaled
to [-1, 1]-ish, 3 classes, libsvm format
(``mllib_multilayer_perceptron_classifier.py:22-23``,
``pytorch_multilayer_perceptron.py:56-66``). That file is iris rescaled;
this stand-in has the same shape/format/separability: three Gaussian blobs
(50 rows each, interleaved) clipped to [-1, 1], features rounded to 6
decimals so the file is byte-stable.

    python assets/make_golden_libsvm.py   # rewrites the .txt in place
"""

import os

import numpy as np

CENTERS = np.array(
    [
        [-0.6, -0.5, 0.5, 0.4],
        [0.0, 0.6, -0.4, -0.6],
        [0.6, -0.4, -0.5, 0.6],
    ]
)
N_PER_CLASS = 50
SCALE = 0.18


def main() -> str:
    rng = np.random.default_rng(1234)
    rows = []
    # Interleave classes (the Spark sample is not class-sorted either) so
    # any prefix split keeps all three classes represented.
    for i in range(N_PER_CLASS):
        for label in range(3):
            feats = CENTERS[label] + rng.normal(0, SCALE, 4)
            feats = np.clip(np.round(feats, 6), -1.0, 1.0)
            cols = " ".join(f"{j + 1}:{v:.6f}" for j, v in enumerate(feats))
            rows.append(f"{label}.0 {cols}")
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "sample_multiclass_classification_data.txt",
    )
    with open(path, "w") as f:
        f.write("\n".join(rows) + "\n")
    return path


if __name__ == "__main__":
    print(main())
