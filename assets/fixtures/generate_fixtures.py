"""Generate the committed real-format fixture corpora.

This image has no network egress, so the reference's ``download=True``
datasets (FashionMNIST ``pytorch_cnn.py:53-69``, AG_NEWS
``pytorch_lstm.py:46-47``, Multi30k ``pytorch_machine_translator.py:14-17``)
cannot be fetched. These fixtures are generated-but-realistic stand-ins in
the EXACT on-disk formats the loaders parse (idx3/idx1 gz, torchtext
AG_NEWS csv, Multi30k parallel text), so the real-file ingestion paths —
not just the synthetic generators — are exercised end to end, and
loss/accuracy-trajectory parity (PARITY.md) runs on file-loaded corpora.

Deterministic: re-running reproduces the committed bytes.

    python assets/fixtures/generate_fixtures.py
"""

from __future__ import annotations

import csv
import gzip
import os
import struct

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


# ---------------------------------------------------------------- idx images


def _draw_garment(rng: np.random.Generator, label: int) -> np.ndarray:
    """A 28×28 grayscale 'garment': each class is a distinct silhouette
    (boxy shirt, trouser columns, bag rectangle, boot L-shape, …) with
    per-example jitter — FashionMNIST-like structure, learnable by TinyVGG."""
    img = np.zeros((28, 28), np.float32)
    j = lambda a, b: int(rng.integers(a, b + 1))  # inclusive jitter

    if label == 0:  # t-shirt: torso + short sleeves
        img[8 + j(-1, 1) : 24, 9:19] = 0.8
        img[8 + j(-1, 1) : 13, 4:24] = 0.7
    elif label == 1:  # trouser: two columns
        img[6:26, 9 + j(-1, 1) : 13] = 0.8
        img[6:26, 15:19] = 0.8
        img[4:8, 9:19] = 0.7
    elif label == 2:  # pullover: torso + long sleeves
        img[7:24, 9:19] = 0.75
        img[7:22, 4 + j(-1, 1) : 8] = 0.65
        img[7:22, 20:24] = 0.65
    elif label == 3:  # dress: narrow top widening down
        for r in range(6, 25):
            half = 2 + (r - 6) * 5 // 18
            img[r, 14 - half : 14 + half] = 0.8
    elif label == 4:  # coat: wide torso + collar gap
        img[6:25, 7:21] = 0.7
        img[6:25, 13 + j(-1, 1) : 15] = 0.2
    elif label == 5:  # sandal: thin diagonal straps
        for k in range(4):
            r = 18 + k * 2
            img[r : r + 1, 5 + k * 2 : 23 - k] = 0.85
    elif label == 6:  # shirt: torso + button line
        img[7:24, 9:19] = 0.7
        img[7:24, 13:15] = 0.95
        img[7:12, 5:23] = 0.6
    elif label == 7:  # sneaker: low wedge
        img[18:24, 4:24] = 0.8
        img[15:18, 10 + j(-1, 1) : 24] = 0.6
    elif label == 8:  # bag: rectangle + handle arc
        img[12:24, 6:22] = 0.8
        img[8:12, 10:12] = 0.7
        img[8:12, 16:18] = 0.7
        img[8:10, 10:18] = 0.7
    else:  # ankle boot: L-shape
        img[8:24, 14 + j(-1, 1) : 20] = 0.8
        img[19:24, 5:20] = 0.8

    img += rng.normal(0.0, 0.05, img.shape).astype(np.float32)
    # small translation jitter
    img = np.roll(img, (j(-1, 1), j(-1, 1)), axis=(0, 1))
    return (np.clip(img, 0.0, 1.0) * 255).astype(np.uint8)


def _write_idx(path: str, arr: np.ndarray) -> None:
    magic = (0x08 << 8) | arr.ndim  # ubyte dtype code 0x08
    with gzip.GzipFile(path, "wb", mtime=0) as f:  # mtime=0: stable bytes
        f.write(struct.pack(">I", magic))
        f.write(struct.pack(">" + "I" * arr.ndim, *arr.shape))
        f.write(arr.tobytes())


def _draw_cifar(rng: np.random.Generator, label: int) -> np.ndarray:
    """A 32×32×3 'photo': per-class hue + a class-dependent shape over a
    noisy background — CIFAR-like structure, learnable by TinyVGG."""
    img = rng.normal(0.35, 0.1, (32, 32, 3)).astype(np.float32)
    hue = np.zeros(3, np.float32)
    hue[label % 3] = 0.5
    hue[(label // 3) % 3] += 0.25
    r0 = 4 + int(rng.integers(-2, 3))
    c0 = 4 + int(rng.integers(-2, 3))
    size = 14 + (label % 5) * 2
    if label % 2 == 0:  # filled square
        img[r0 : r0 + size, c0 : c0 + size] += hue
    else:  # hollow frame
        img[r0 : r0 + size, c0 : c0 + 3] += hue
        img[r0 : r0 + size, c0 + size - 3 : c0 + size] += hue
        img[r0 : r0 + 3, c0 : c0 + size] += hue
        img[r0 + size - 3 : r0 + size, c0 : c0 + size] += hue
    return (np.clip(img, 0.0, 1.0) * 255).astype(np.uint8)


def make_cifar10(n_train: int = 512, n_test: int = 128) -> None:
    """CIFAR-10 binary layout: 3073-byte records (1 label + 3072 CHW)."""
    rng = np.random.default_rng(99)
    out = os.path.join(HERE, "cifar-10-batches-bin")
    os.makedirs(out, exist_ok=True)
    for name, n in (("data_batch_1.bin", n_train), ("test_batch.bin", n_test)):
        with open(os.path.join(out, name), "wb") as f:
            for _ in range(n):
                label = int(rng.integers(0, 10))
                img = _draw_cifar(rng, label)  # HWC
                f.write(bytes([label]))
                f.write(img.transpose(2, 0, 1).tobytes())  # stored CHW
    print(f"CIFAR-10 fixture: {n_train} train / {n_test} test → {out}")


def make_fashion_mnist(n_train: int = 640, n_test: int = 160) -> None:
    rng = np.random.default_rng(42)
    out = os.path.join(HERE, "FashionMNIST", "raw")
    os.makedirs(out, exist_ok=True)
    for prefix, n in (("train", n_train), ("t10k", n_test)):
        labels = rng.integers(0, 10, n).astype(np.uint8)
        images = np.stack([_draw_garment(rng, int(l)) for l in labels])
        _write_idx(
            os.path.join(out, f"{prefix}-images-idx3-ubyte.gz"), images
        )
        _write_idx(
            os.path.join(out, f"{prefix}-labels-idx1-ubyte.gz"), labels
        )
    print(f"FashionMNIST fixture: {n_train} train / {n_test} test → {out}")


# ---------------------------------------------------------------- AG_NEWS csv

_TOPICS = {
    1: (  # World
        "government election minister parliament treaty embassy summit "
        "sanctions border refugee coalition diplomat".split(),
        "officials capital nation region crisis talks accord".split(),
    ),
    2: (  # Sports
        "match team season coach striker goalkeeper league tournament "
        "championship playoff injury transfer".split(),
        "victory defeat fans stadium final record title".split(),
    ),
    3: (  # Business
        "market shares profit revenue investor bank earnings merger "
        "acquisition stocks inflation quarterly".split(),
        "growth forecast analysts exchange rally slump deal".split(),
    ),
    4: (  # Sci/Tech
        "software chip research quantum network robot satellite browser "
        "processor startup algorithm encryption".split(),
        "launch study prototype upgrade release patent lab".split(),
    ),
}
_FILLER = "the a of and to in on with for said new over from as its after".split()


def _news_sentence(rng, words, extras, n):
    toks = []
    for _ in range(n):
        r = rng.random()
        if r < 0.45:
            toks.append(str(rng.choice(words)))
        elif r < 0.6:
            toks.append(str(rng.choice(extras)))
        else:
            toks.append(str(rng.choice(_FILLER)))
    return " ".join(toks)


def make_ag_news(n_train: int = 480, n_test: int = 120) -> None:
    rng = np.random.default_rng(7)
    out = os.path.join(HERE, "AG_NEWS")
    os.makedirs(out, exist_ok=True)
    for name, n in (("train.csv", n_train), ("test.csv", n_test)):
        with open(os.path.join(out, name), "w", newline="") as f:
            w = csv.writer(f)
            for _ in range(n):
                cls = int(rng.integers(1, 5))
                words, extras = _TOPICS[cls]
                title = _news_sentence(rng, words, extras, int(rng.integers(4, 8)))
                desc = _news_sentence(rng, words, extras, int(rng.integers(16, 28)))
                # Real AG_NEWS rows carry commas inside quoted fields —
                # exercise the csv quoting path.
                if rng.random() < 0.3:
                    desc = desc.replace(" said ", ", said ", 1)
                w.writerow([cls, title, desc])
    print(f"AG_NEWS fixture: {n_train} train / {n_test} test → {out}")


# ---------------------------------------------------------------- Multi30k

# Caption-style templates with a word-aligned mini en→de dictionary —
# Multi30k is image captions ("a man in a blue shirt is riding a horse"),
# and a deterministic alignment keeps the task learnable at fixture scale.
_NOUNS = [
    ("man", "mann"), ("woman", "frau"), ("boy", "junge"), ("girl", "mädchen"),
    ("dog", "hund"), ("horse", "pferd"), ("child", "kind"), ("worker", "arbeiter"),
    ("musician", "musiker"), ("runner", "läufer"), ("vendor", "verkäufer"),
    ("climber", "kletterer"),
]
_COLORS = [
    ("red", "roten"), ("blue", "blauen"), ("green", "grünen"),
    ("yellow", "gelben"), ("black", "schwarzen"), ("white", "weißen"),
]
_GARMENTS = [
    ("shirt", "hemd"), ("jacket", "jacke"), ("hat", "hut"), ("coat", "mantel"),
]
_VERBS = [
    ("is riding", "reitet"), ("is walking", "geht"), ("is holding", "hält"),
    ("is climbing", "klettert"), ("is playing", "spielt"),
    ("is watching", "beobachtet"), ("is pulling", "zieht"),
]
_PLACES = [
    ("on the street", "auf der straße"), ("in the park", "im park"),
    ("near the river", "am fluss"), ("at the market", "auf dem markt"),
    ("on a mountain", "auf einem berg"), ("in the city", "in der stadt"),
]
_OBJECTS = [
    ("a bicycle", "ein fahrrad"), ("a guitar", "eine gitarre"),
    ("a rope", "ein seil"), ("a ball", "einen ball"),
    ("a cart", "einen karren"), ("a kite", "einen drachen"),
]


def _caption(rng) -> tuple[str, str]:
    n_en, n_de = _NOUNS[rng.integers(0, len(_NOUNS))]
    c_en, c_de = _COLORS[rng.integers(0, len(_COLORS))]
    g_en, g_de = _GARMENTS[rng.integers(0, len(_GARMENTS))]
    v_en, v_de = _VERBS[rng.integers(0, len(_VERBS))]
    p_en, p_de = _PLACES[rng.integers(0, len(_PLACES))]
    o_en, o_de = _OBJECTS[rng.integers(0, len(_OBJECTS))]
    form = rng.integers(0, 3)
    if form == 0:
        en = f"a {n_en} in a {c_en} {g_en} {v_en} {o_en} {p_en} ."
        de = f"ein {n_de} in einem {c_de} {g_de} {v_de} {o_de} {p_de} ."
    elif form == 1:
        en = f"a {n_en} {v_en} {o_en} {p_en} ."
        de = f"ein {n_de} {v_de} {o_de} {p_de} ."
    else:
        en = f"the {n_en} in the {c_en} {g_en} {v_en} {p_en} ."
        de = f"der {n_de} in dem {c_de} {g_de} {v_de} {p_de} ."
    return en, de


def make_multi30k(n_train: int = 400, n_valid: int = 80) -> None:
    rng = np.random.default_rng(30)
    out = os.path.join(HERE, "multi30k")
    os.makedirs(out, exist_ok=True)
    for split, n in (("train", n_train), ("valid", n_valid)):
        with open(os.path.join(out, f"{split}.en"), "w") as fe, open(
            os.path.join(out, f"{split}.de"), "w"
        ) as fd:
            for _ in range(n):
                en, de = _caption(rng)
                fe.write(en + "\n")
                fd.write(de + "\n")
    print(f"Multi30k fixture: {n_train} train / {n_valid} valid → {out}")


if __name__ == "__main__":
    make_fashion_mnist()
    make_ag_news()
    make_multi30k()
    make_cifar10()
